"""QoS control plane (engine/qos.py): budgeting, admission, coalescing.

Pins the subsystem's contracts:

- **byte-identity** — with QoS on (and the ingest partition actively
  clipping drains), the consolidated outputs for all admitted traffic
  are identical to QoS-off: deferral moves rows to later ticks, never
  drops, duplicates or alters them;
- **visible shedding** — every shed query is counted in ``shed_total``
  AND answered with a 503 carrying ``Retry-After`` + the request id
  (the unified 503 contract the router shares);
- **seal alignment under partial drains** — the recording session's
  seals cover exactly the drained prefix at any clip point, so a
  checkpoint can never cover a deferred-but-unprocessed row;
- **coalescing accounting** — concurrent as-of-now queries sharing one
  kernel dispatch are counted, revise-mode re-answers are not;
- **PWT013** — SLO configured + QoS disabled warns (measuring without
  acting), with the explicit-opt-out waiver and both TN squares.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.qos import (QosConfig, QosController,
                                    QueryShedError, current_controller,
                                    install_controller, resolve_qos)
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _fresh():
    G.clear()
    install_controller(None)
    yield
    G.clear()
    install_controller(None)


class _FakeTracker:
    """Minimal RequestTracker stand-in: the controller reads slo_ms,
    burn_rate(), window_size() and quantiles_ms()."""

    def __init__(self, slo_ms=20.0, burn=0.0, p50=None, window=256):
        self.slo_ms = slo_ms
        self.burn = burn
        self.p50 = p50
        self.window = window

    def burn_rate(self):
        return self.burn

    def window_size(self):
        return self.window

    def quantiles_ms(self):
        if self.p50 is None:
            return None
        return {0.5: self.p50, 0.95: self.p50 * 2, 0.99: self.p50 * 3}


def _controller(*, burn=0.0, p50=None, slo=20.0, window=256, **cfg_kwargs):
    cfg = QosConfig(**cfg_kwargs)
    return QosController(cfg, _FakeTracker(slo_ms=slo, burn=burn,
                                           p50=p50, window=window)), cfg


# ---------------------------------------------------------------------------
# config + admission control
# ---------------------------------------------------------------------------

def test_config_env_knobs(monkeypatch):
    monkeypatch.setenv("PATHWAY_QOS_QUERY_BUDGET", "12.5")
    monkeypatch.setenv("PATHWAY_QOS_ADMISSION_QUEUE", "7")
    monkeypatch.setenv("PATHWAY_QOS_MIN_INGEST_ROWS", "3")
    cfg = QosConfig.from_env()
    assert cfg.query_budget_ms == 12.5
    assert cfg.admission_queue == 7
    assert cfg.min_ingest_rows == 3
    monkeypatch.setenv("PATHWAY_QOS_QUERY_BUDGET", "adaptive")
    assert QosConfig.from_env().query_budget_ms is None


def test_resolve_qos_tristate(monkeypatch):
    monkeypatch.delenv("PATHWAY_QOS", raising=False)
    assert resolve_qos(None) is None          # default: off
    assert resolve_qos(False) is None         # explicit opt-out
    assert isinstance(resolve_qos(True), QosConfig)
    cfg = QosConfig()
    assert resolve_qos(cfg) is cfg
    monkeypatch.setenv("PATHWAY_QOS", "1")
    assert isinstance(resolve_qos(None), QosConfig)
    monkeypatch.setenv("PATHWAY_QOS", "0")
    assert resolve_qos(None) is None
    with pytest.raises(TypeError):
        resolve_qos("yes")


def test_admission_queue_full_sheds_and_frees():
    ctl, _ = _controller(admission_queue=1)
    ctl.admit(time.perf_counter())           # fills the single slot
    with pytest.raises(QueryShedError) as ei:
        ctl.admit(time.perf_counter())
    assert ei.value.retry_after_s >= 1
    assert ctl.shed_total == 1
    assert ctl.admitted_total == 1
    ctl.finish_query()                        # slot freed
    ctl.admit(time.perf_counter())
    assert ctl.admitted_total == 2
    assert ctl.shed_total == 1                # no silent extra counting


def test_admission_deadline_shed_under_burn():
    # burning budget + predicted latency past the deadline (default:
    # 5x the SLO target — client patience, not the latency target)
    # -> fast 503
    ctl, _ = _controller(burn=5.0, p50=600.0, slo=20.0)
    with pytest.raises(QueryShedError):
        ctl.admit(time.perf_counter())
    assert ctl.shed_total == 1
    # same prediction but healthy burn -> admitted (the queue, not the
    # gate, absorbs it)
    ctl2, _ = _controller(burn=0.1, p50=600.0, slo=20.0)
    ctl2.admit(time.perf_counter())
    assert ctl2.shed_total == 0
    # burning but predicted well under the deadline -> admitted (a
    # degraded-but-fast system serves; only hopeless queries shed)
    ctl3, _ = _controller(burn=5.0, p50=30.0, slo=20.0)
    ctl3.admit(time.perf_counter())
    assert ctl3.shed_total == 0
    # burn without statistical footing never sheds: one compile-time
    # outlier in a tiny window must not wedge the gate shut
    ctl4, _ = _controller(burn=100.0, p50=600.0, slo=20.0, window=1)
    ctl4.admit(time.perf_counter())
    assert ctl4.shed_total == 0


def test_shedding_flag_tracks_burn_and_queue():
    ctl, cfg = _controller(burn=5.0, p50=100.0)
    assert not ctl.is_shedding()              # not serving yet
    ctl._serving_active_until = time.monotonic() + 60
    assert ctl.is_shedding()                  # burn past threshold
    ctl2, cfg2 = _controller(admission_queue=1)
    ctl2.admit(time.perf_counter())
    assert ctl2.is_shedding()                 # queue at cap


# ---------------------------------------------------------------------------
# device-time budgeting
# ---------------------------------------------------------------------------

def test_ingest_bounded_by_ceiling_without_serving():
    # outside a serving phase the partition sits at its ceiling — never
    # unlimited: with QoS armed, max_ingest_rows bounds any single
    # tick's ingest batch (a bulk-push between ticks must not hand the
    # next tick a monster drain)
    ctl, cfg = _controller()
    assert ctl.ingest_row_budget() == cfg.max_ingest_rows


def test_aimd_feedback_halves_and_regrows():
    ctl, cfg = _controller(burn=5.0, p50=100.0, slo=20.0,
                           min_ingest_rows=8, max_ingest_rows=1024)
    ctl._serving_active_until = time.monotonic() + 60
    start = ctl.ingest_row_budget()
    assert start == 1024
    ctl.on_tick(ingest_rows=100, deferred=False, tick_ms=10.0)
    assert ctl.ingest_row_budget() == 512     # multiplicative decrease
    for _ in range(12):
        ctl.on_tick(ingest_rows=100, deferred=False, tick_ms=10.0)
    assert ctl.ingest_row_budget() == cfg.min_ingest_rows  # floor holds
    ctl.tracker.burn = 0.0                    # pressure gone
    ctl.tracker.p50 = 1.0
    for _ in range(40):
        ctl.on_tick(ingest_rows=100, deferred=False, tick_ms=10.0)
    assert ctl.ingest_row_budget() == cfg.max_ingest_rows  # regrown


def test_fixed_budget_translates_ms_to_rows():
    ctl, _ = _controller(query_budget_ms=60.0, min_ingest_rows=1,
                         max_ingest_rows=10_000)
    ctl.tick_interval_ms = 100.0
    ctl._serving_active_until = time.monotonic() + 60
    # learn the cost: ingest-only ticks at 0.1 ms/row
    for _ in range(20):
        ctl.on_tick(ingest_rows=100, deferred=False, tick_ms=10.0,
                    device_ms=10.0, queries_in_tick=0)
    # 100 ms tick - 60 ms query budget = 40 ms ingest at ~0.1 ms/row
    assert ctl.ingest_row_budget() == pytest.approx(400, rel=0.25)
    assert ctl.query_budget_ms() == 60.0


def test_budget_relaxes_gradually_when_serving_stops():
    ctl, cfg = _controller(burn=5.0, p50=100.0, min_ingest_rows=8,
                           max_ingest_rows=1024)
    ctl._serving_active_until = time.monotonic() + 0.05
    for _ in range(10):                       # drive to the floor
        ctl.on_tick(ingest_rows=10, deferred=True, tick_ms=5.0)
    assert ctl.ingest_row_budget() == cfg.min_ingest_rows
    time.sleep(0.06)                          # serving window expires
    # relaxation is GRADUAL: the deferred backlog drains over bounded
    # ticks (x4/tick), never one monster tick — and even fully relaxed
    # the allowance tops out at the ceiling, never unlimited
    ctl.on_tick(ingest_rows=10, deferred=False, tick_ms=5.0)
    first = ctl.ingest_row_budget()
    assert first < 1024
    for _ in range(5):
        ctl.on_tick(ingest_rows=10, deferred=False, tick_ms=5.0)
    assert ctl.ingest_row_budget() == cfg.max_ingest_rows
    assert not ctl.backpressure_active


# ---------------------------------------------------------------------------
# partial drains: Session + recording-session seal alignment
# ---------------------------------------------------------------------------

def test_session_partial_drain_keeps_backlog():
    from pathway_tpu.io._datasource import Session

    s = Session()
    for i in range(10):
        s.push(i, (i,), 1)
    first = s.drain(4)
    assert [k for k, _r, _d in first] == [0, 1, 2, 3]
    assert s.backlog() == 6
    assert len(s.drain(None)) == 6
    assert s.backlog() == 0
    assert s.drain(0) == []


def test_recording_session_seals_cover_exactly_the_drained_prefix():
    from pathway_tpu.engine.persistence import _RecordingSession
    from pathway_tpu.io._datasource import Session

    inner = Session()
    rec = _RecordingSession(inner, skip=0)
    for i in range(10):
        rec.push(i, (i,), 1, offset=i)
    # tick 1 drains only 4 rows: the seal must cover exactly those 4
    drained = rec.seal_drain(1, limit=4)
    assert len(drained) == 4
    taken = rec.take_sealed(1)
    assert [e[0] for e in taken] == [0, 1, 2, 3]
    # the 6 deferred rows were NOT durable-eligible at tick 1
    assert rec.take_sealed(1) == []
    # tick 2 drains the rest (plus 2 new pushes mid-flight)
    rec.push(10, (10,), 1, offset=10)
    rec.push(11, (11,), 1, offset=11)
    drained2 = rec.seal_drain(2)
    assert len(drained2) == 8
    taken2 = rec.take_sealed(2)
    assert [e[0] for e in taken2] == [4, 5, 6, 7, 8, 9, 10, 11]
    assert rec.pending == []


def test_recording_session_partial_then_watermark_lag():
    """A frozen watermark must hold back ONLY undrained/later seals —
    the partial-drain bookkeeping keeps earlier ticks takeable."""
    from pathway_tpu.engine.persistence import _RecordingSession
    from pathway_tpu.io._datasource import Session

    inner = Session()
    rec = _RecordingSession(inner, skip=0)
    for i in range(6):
        rec.push(i, (i,), 1, offset=i)
    rec.seal_drain(1, limit=2)
    rec.seal_drain(2, limit=2)
    rec.seal_drain(3)
    # watermark at 2: ticks 1+2 durable-eligible, tick 3's rows held
    taken = rec.take_sealed(2)
    assert [e[0] for e in taken] == [0, 1, 2, 3]
    assert [e[0] for e in rec.take_sealed(3)] == [4, 5]


# ---------------------------------------------------------------------------
# coalescing accounting
# ---------------------------------------------------------------------------

class _FakeIndex:
    def __init__(self):
        self.search_calls = 0

    def add(self, key, vec, filt):
        pass

    def remove(self, key):
        pass

    def search(self, queries):
        self.search_calls += 1
        return [((key, 0.0),) for key, _v, _l, _f in queries]


def test_coalesced_queries_counted_once_per_dispatch():
    from pathway_tpu.engine.delta import Delta
    from pathway_tpu.engine.index_ops import ExternalIndexOperator

    ctl, _ = _controller()
    install_controller(ctl)
    idx = _FakeIndex()
    op = ExternalIndexOperator(idx, data_vec_pos=0, data_filter_pos=None,
                               query_vec_pos=0, query_limit_pos=None,
                               query_filter_pos=None)
    queries = Delta([(i, ([0.0],), 1) for i in range(3)])
    op.step(1, [Delta(), queries])
    assert idx.search_calls == 1              # ONE kernel dispatch
    assert ctl.coalesced_dispatches == 1
    assert ctl.coalesced_queries == 3
    # a single query is not "coalesced"
    op.step(2, [Delta(), Delta([(9, ([0.0],), 1)])])
    assert ctl.coalesced_dispatches == 1


def test_revise_mode_reanswers_not_counted():
    from pathway_tpu.engine.delta import Delta
    from pathway_tpu.engine.index_ops import ExternalIndexOperator

    ctl, _ = _controller()
    install_controller(ctl)
    op = ExternalIndexOperator(_FakeIndex(), data_vec_pos=0,
                               data_filter_pos=None, query_vec_pos=0,
                               query_limit_pos=None, query_filter_pos=None,
                               revise=True)
    queries = Delta([(i, ([0.0],), 1) for i in range(3)])
    op.step(1, [Delta(), queries])
    assert ctl.coalesced_dispatches == 0      # standing-query re-answers


def test_hook_is_noop_without_controller():
    from pathway_tpu.engine.qos import note_coalesced_dispatch

    note_coalesced_dispatch(5)                # must not raise
    assert current_controller() is None


# ---------------------------------------------------------------------------
# end to end: byte-identity + deferral + visible shedding
# ---------------------------------------------------------------------------

def _run_counts(monkeypatch, words, *, qos_env: bool) -> tuple[dict, dict]:
    """Stream word rows, return (final counts, qos counters)."""
    from pathway_tpu.testing.faults import flaky_subject

    G.clear()
    if qos_env:
        monkeypatch.setenv("PATHWAY_QOS", "1")
        # force the partition (no live HTTP queries in this test) and
        # clamp it tight so a 300-row burst MUST defer across ticks
        monkeypatch.setenv("PATHWAY_QOS_ALWAYS_BUDGET", "1")
        monkeypatch.setenv("PATHWAY_QOS_MIN_INGEST_ROWS", "16")
        monkeypatch.setenv("PATHWAY_QOS_MAX_INGEST_ROWS", "16")
    else:
        monkeypatch.delenv("PATHWAY_QOS", raising=False)
        monkeypatch.delenv("PATHWAY_QOS_ALWAYS_BUDGET", raising=False)
    t = pw.io.python.read(
        flaky_subject([{"word": w} for w in words], fail_after=0,
                      fail_attempts=0),
        schema=pw.schema_from_types(word=str), autocommit_duration_ms=5)
    counts = t.groupby(t.word).reduce(word=t.word, c=pw.reducers.count())
    state: dict[str, int] = {}
    captured: list = []

    def on_change(key, row, time, is_addition):
        if not captured:
            ctl = current_controller()
            if ctl is not None:
                captured.append(ctl)
        if is_addition:
            state[row["word"]] = row["c"]
        elif state.get(row["word"]) == row["c"]:
            del state[row["word"]]

    pw.io.subscribe(counts, on_change)
    pw.run()
    qstats = captured[0].summary() if captured else {}
    return state, qstats


def test_e2e_identity_with_forced_deferral(monkeypatch):
    """The acceptance invariant: consolidated outputs of admitted
    traffic are identical QoS-on vs QoS-off, while the controller
    demonstrably deferred ingest (rows rode later ticks)."""
    words = [f"w{i % 37}" for i in range(300)]
    base, _ = _run_counts(monkeypatch, words, qos_env=False)
    qos, qstats = _run_counts(monkeypatch, words, qos_env=True)
    assert qos == base                        # nothing dropped or altered
    assert sum(base.values()) == 300
    assert qstats["ingest_deferrals"] >= 1    # the clip actually happened
    assert qstats["deferred_rows_total"] >= 1
    assert qstats["shed_total"] == 0          # ingest defers, never sheds


def test_e2e_shed_is_visible_503_with_retry_after(monkeypatch):
    """A shed query = 503 + Retry-After + X-Pathway-Request-Id AND a
    shed_total increment — never a silent drop."""
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    monkeypatch.setenv("PATHWAY_QOS", "1")
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "1")
    ws = PathwayWebserver(host="127.0.0.1", port=0)
    schema = sch.schema_from_types(query=str)
    table, writer = rest_connector(
        webserver=ws, route="/q", schema=schema, methods=("POST",),
        delete_completed_queries=True, autocommit_duration_ms=10)
    writer(table.select(result=pw.apply(str.upper, table.query)))

    errors: list = []

    def _run():
        try:
            pw.run()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    th = threading.Thread(target=_run, daemon=True)
    th.start()
    deadline = time.monotonic() + 20.0
    rt = None
    while time.monotonic() < deadline:
        live = list(_streaming._ACTIVE_RUNTIMES)
        if live and ws._started.is_set() and ws.port \
                and getattr(live[0], "qos", None) is not None:
            rt = live[0]
            break
        time.sleep(0.02)
    try:
        assert rt is not None and not errors, f"no runtime: {errors}"

        def ask(q):
            req = urllib.request.Request(
                f"http://127.0.0.1:{ws.port}/q",
                data=json.dumps({"query": q}).encode(), method="POST",
                headers={"Content-Type": "application/json"})
            return urllib.request.urlopen(req, timeout=15)

        with ask("ok") as resp:               # healthy baseline
            assert resp.status == 200

        # force the gate shut: queue pinned at its cap
        rt.qos._queue_depth = rt.qos.config.admission_queue
        with pytest.raises(urllib.error.HTTPError) as ei:
            ask("shed-me")
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert ei.value.headers["X-Pathway-Request-Id"]
        assert rt.qos.shed_total == 1
        rt.qos._queue_depth = 0               # gate open again
        with ask("ok2") as resp:
            assert resp.status == 200
            assert resp.read() == b"OK2"
        assert rt.qos.admitted_total == 2
    finally:
        _streaming.stop_all()
        th.join(10.0)
        G.clear()
    assert not errors, f"pipeline failed: {errors}"


def test_admission_wait_stage_telescopes():
    """The new stage slots into the decomposition without breaking the
    sum-to-e2e contract (satellite: tracker admission_wait)."""
    from pathway_tpu.engine.request_tracker import (STAGES, RequestSpan,
                                                    RequestTracker)

    assert "admission_wait" in STAGES
    tr = RequestTracker(slo_ms=1000.0)
    span = tr.start("rid-1", "/q", t_ingress=100.0)
    span.t_admission = 100.010   # 10 ms parse/validate
    span.t_enqueued = 100.060    # 50 ms queued at the admission gate
    span.t_tick_start = 100.070
    span.t_host_done = 100.080
    span.t_resolved = 100.090
    span.t_responded = 100.100
    stages = span.stages_ms()
    assert stages["ingress_wait"] == pytest.approx(10.0)
    assert stages["admission_wait"] == pytest.approx(50.0)
    assert sum(stages.values()) == pytest.approx(
        (span.t_responded - span.t_ingress) * 1e3)
    # QoS off: no admission stamp -> the stage reads 0, still telescopes
    span2 = tr.start("rid-2", "/q", t_ingress=5.0)
    span2.t_enqueued = 5.020
    span2.t_resolved = 5.030
    span2.t_responded = 5.040
    s2 = span2.stages_ms()
    assert s2["admission_wait"] == pytest.approx(20.0)  # snaps into gap
    assert sum(s2.values()) == pytest.approx(40.0)


# ---------------------------------------------------------------------------
# backpressure propagation
# ---------------------------------------------------------------------------

def test_supervisor_backpressure_spares_serving_sources():
    from pathway_tpu.engine.supervisor import ConnectorSupervisor
    from pathway_tpu.io._datasource import DataSource, Session

    class _Ingest(DataSource):
        name = "ingest"

    class _Serving(DataSource):
        name = "serving"
        request_tracker = None  # the serving marker slot

    sup = ConnectorSupervisor()
    schema = pw.schema_from_types(x=int)
    e1 = sup.add_source(None, _Ingest(schema), Session(), Session())
    e2 = sup.add_source(None, _Serving(schema), Session(), Session())
    sup.apply_backpressure(True)
    assert e1.backpressure.is_set()
    assert not e2.backpressure.is_set()       # never throttle queries
    sup.apply_backpressure(False)
    assert not e1.backpressure.is_set()


def test_session_sleep_stretches_under_backpressure():
    from pathway_tpu.io._datasource import Session

    s = Session()
    s.backpressure_factor = 5.0
    t0 = time.perf_counter()
    assert s.sleep(0.01)
    fast = time.perf_counter() - t0
    s.backpressure.set()
    t0 = time.perf_counter()
    assert s.sleep(0.01)
    slow = time.perf_counter() - t0
    assert slow >= 0.045 > fast


# ---------------------------------------------------------------------------
# PWT013: measuring without acting
# ---------------------------------------------------------------------------

def _serving_graph():
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    ws = PathwayWebserver(host="127.0.0.1", port=0)
    table, writer = rest_connector(
        webserver=ws, route="/q",
        schema=sch.schema_from_types(query=str), methods=("POST",))
    writer(table.select(result=pw.apply(str.upper, table.query)))


def _codes(**kwargs):
    return {d.code for d in pw.static_check(**kwargs)}


def test_pwt013_tp_slo_set_qos_unset(monkeypatch):
    monkeypatch.setenv("PATHWAY_SLO_E2E_MS", "20")
    monkeypatch.delenv("PATHWAY_QOS", raising=False)
    _serving_graph()
    assert "PWT013" in _codes()


def test_pwt013_tn_qos_enabled(monkeypatch):
    monkeypatch.setenv("PATHWAY_SLO_E2E_MS", "20")
    monkeypatch.setenv("PATHWAY_QOS", "1")
    _serving_graph()
    assert "PWT013" not in _codes()


def test_pwt013_waiver_explicit_opt_out(monkeypatch):
    # PATHWAY_QOS=0 is a DECISION (the documented waiver): no warning
    monkeypatch.setenv("PATHWAY_SLO_E2E_MS", "20")
    monkeypatch.setenv("PATHWAY_QOS", "0")
    _serving_graph()
    assert "PWT013" not in _codes()
    # the API argument waives the same way
    monkeypatch.delenv("PATHWAY_QOS", raising=False)
    assert "PWT013" not in _codes(qos=False)


def test_pwt013_tn_no_slo_or_no_serving(monkeypatch):
    monkeypatch.delenv("PATHWAY_SLO_E2E_MS", raising=False)
    monkeypatch.delenv("PATHWAY_QOS", raising=False)
    _serving_graph()
    assert "PWT013" not in _codes()           # nothing measured: no loop
    G.clear()
    monkeypatch.setenv("PATHWAY_SLO_E2E_MS", "20")
    from pathway_tpu.testing.faults import flaky_subject

    t = pw.io.python.read(
        flaky_subject([{"word": "a"}], fail_after=0, fail_attempts=0),
        schema=pw.schema_from_types(word=str))
    pw.io.subscribe(t, lambda *a, **k: None)
    assert "PWT013" not in _codes()           # pure ETL: nothing serves


# ---------------------------------------------------------------------------
# exposition: pathway_tpu_qos_* families
# ---------------------------------------------------------------------------

def test_qos_metrics_families_and_status(monkeypatch):
    from pathway_tpu.engine.http_server import MonitoringHttpServer
    from tests.test_monitoring_http import _parse_samples

    ctl, _ = _controller()
    ctl.shed_total = 3
    ctl.ingest_deferrals = 7
    ctl.coalesced_queries = 12
    ctl.coalesced_dispatches = 4

    class _RT:
        qos = ctl
        sessions: list = []

        class scheduler:
            recorder = None
            stats: dict = {}

        class runner:
            class graph:
                nodes: list = []

    server = MonitoringHttpServer(_RT(), port=0)
    lines = server.metrics_payload().splitlines()
    samples = _parse_samples(lines)           # regex lint over every line
    vals = {f: v for f, _l, v in samples}
    assert vals["pathway_tpu_qos_shed_total"] == 3.0
    assert vals["pathway_tpu_qos_ingest_deferrals"] == 7.0
    assert vals["pathway_tpu_qos_coalesced_queries"] == 12.0
    assert vals["pathway_tpu_qos_admission_queue_depth"] == 0.0
    assert "pathway_tpu_qos_query_budget_ms" in vals
    typed = {ln.split()[2] for ln in lines if ln.startswith("# TYPE")}
    for fam in ("pathway_tpu_qos_query_budget_ms",
                "pathway_tpu_qos_ingest_deferrals",
                "pathway_tpu_qos_shed_total",
                "pathway_tpu_qos_coalesced_queries",
                "pathway_tpu_qos_admission_queue_depth"):
        assert fam in typed, f"{fam} has no # TYPE line"
    status = server.status_payload()
    assert status["qos"]["shed_total"] == 3
    assert status["qos"]["enabled"] is True
    assert status["qos"]["mode"] == "adaptive"


# ---------------------------------------------------------------------------
# fleet integration: heartbeat QoS state steers the router
# ---------------------------------------------------------------------------

def test_router_steers_away_from_shedding_endpoint():
    import socket

    from pathway_tpu.engine.router import QueryRouter, ReplicaEndpoint

    router = QueryRouter()
    socks = []

    def _ep(rid, p50, shedding):
        a, b = socket.socketpair()
        socks.append((a, b))
        ep = ReplicaEndpoint(rid, "replica", "127.0.0.1", 1, a)
        for _ in range(8):
            ep.observe(p50)
        ep.apply_heartbeat({"qos": {"shedding": shedding,
                                    "shed_total": 5 if shedding else 0}})
        router._endpoints[rid] = ep
        return ep

    try:
        fast_shedding = _ep("fast-shedding", 1.0, True)
        slow_healthy = _ep("slow-healthy", 50.0, False)
        # the fast endpoint is actively shedding: the router must steer
        # to the slower healthy one BEFORE p95 ever degrades
        assert router.choose().replica_id == "slow-healthy"
        # availability wins when the WHOLE fleet sheds
        slow_healthy.apply_heartbeat({"qos": {"shedding": True}})
        assert router.choose().replica_id in ("fast-shedding",
                                              "slow-healthy")
        # recovery: the heartbeat clears the flag, endpoint rejoins
        fast_shedding.apply_heartbeat({"qos": {"shedding": False}})
        assert router.choose().replica_id == "fast-shedding"
        # /fleet/status shows per-endpoint QoS state
        fleet = router.fleet_status_payload()["fleet"]
        by_id = {e["replica"]: e for e in fleet}
        assert by_id["slow-healthy"]["qos"]["shedding"] is True
        assert by_id["fast-shedding"]["qos"]["shedding"] is False
    finally:
        for a, b in socks:
            a.close()
            b.close()


def test_heartbeat_payload_carries_qos_state():
    from pathway_tpu.engine.replica import ControlClient

    ctl, _ = _controller()
    ctl.shed_total = 2

    class _RT:
        qos = ctl
        sessions: list = []
        recorder = None
        persistence = None
        replica = None
        http_server = None

    client = ControlClient.__new__(ControlClient)
    client.runtime = _RT()
    client.replica_id = "r1"
    client.role = "replica"
    hb = client._heartbeat_payload()
    assert hb["qos"]["shed_total"] == 2
    assert hb["qos"]["shedding"] is False
    assert "query_budget_ms" in hb["qos"]
