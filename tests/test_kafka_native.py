"""Native Kafka wire protocol (io/kafka/_protocol.py) against an in-test
broker speaking the same subset: ApiVersions/Metadata/ListOffsets/Fetch/
Produce with RecordBatch v2. The broker decodes requests with the shared
Reader and re-encodes record batches itself, so framing, varints and
CRC32C are exercised in both directions (SURVEY §4: fakes stand in for
real services)."""

from __future__ import annotations

import socket
import struct
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.kafka import _protocol as kp


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


class FakeBroker:
    """Single-node broker: in-memory partition logs."""

    def __init__(self, topics: dict[str, int]):
        # topic -> [partition logs]; log = list[(key, value)]
        self.logs = {t: [[] for _ in range(n)] for t, n in topics.items()}
        self.force_error = None  # (partition, code): next fetch fails there
        self.server = socket.create_server(("127.0.0.1", 0))
        self.port = self.server.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                raw = self._read_exact(conn, 4)
                (length,) = struct.unpack(">i", raw)
                payload = self._read_exact(conn, length)
                r = kp.Reader(payload)
                api_key = r.int16()
                api_version = r.int16()
                corr = r.int32()
                r.string()  # client id
                body = self._dispatch(api_key, api_version, r)
                resp = kp.enc_int32(corr) + body
                conn.sendall(kp.enc_int32(len(resp)) + resp)
        except (ConnectionError, OSError):
            return

    @staticmethod
    def _read_exact(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _dispatch(self, api_key, api_version, r: kp.Reader) -> bytes:
        if api_key == kp.API_VERSIONS:
            keys = [kp.API_PRODUCE, kp.API_FETCH, kp.API_LIST_OFFSETS,
                    kp.API_METADATA, kp.API_VERSIONS]
            out = kp.enc_int16(0) + kp.enc_int32(len(keys))
            for k in keys:
                out += kp.enc_int16(k) + kp.enc_int16(0) + kp.enc_int16(4)
            return out
        if api_key == kp.API_METADATA:
            n = r.int32()
            wanted = [r.string() for _ in range(n)]
            out = kp.enc_int32(1)  # brokers
            out += (kp.enc_int32(0) + kp.enc_string("127.0.0.1")
                    + kp.enc_int32(self.port) + kp.enc_string(None))
            out += kp.enc_int32(0)  # controller id
            out += kp.enc_int32(len(wanted))
            for t in wanted:
                logs = self.logs.get(t)
                out += kp.enc_int16(0 if logs is not None else 3)
                out += kp.enc_string(t) + kp.enc_int8(0)
                out += kp.enc_int32(len(logs or []))
                for pid in range(len(logs or [])):
                    out += (kp.enc_int16(0) + kp.enc_int32(pid)
                            + kp.enc_int32(0) + kp.enc_int32(0)
                            + kp.enc_int32(0))
            return out
        if api_key == kp.API_LIST_OFFSETS:
            r.int32()  # replica
            r.int32()  # topic count (assume 1)
            topic = r.string()
            r.int32()  # partition count (assume 1)
            pid = r.int32()
            ts = r.int64()
            log = self.logs[topic][pid]
            offset = 0 if ts == -2 else len(log)
            return (kp.enc_int32(1) + kp.enc_string(topic) + kp.enc_int32(1)
                    + kp.enc_int32(pid) + kp.enc_int16(0) + kp.enc_int64(-1)
                    + kp.enc_int64(offset))
        if api_key == kp.API_FETCH:
            r.int32()  # replica
            r.int32()  # max wait
            r.int32()  # min bytes
            r.int32()  # max bytes
            r.int8()   # isolation
            r.int32()  # topic count (assume 1)
            topic = r.string()
            n_parts = r.int32()
            wanted = []
            for _ in range(n_parts):
                pid = r.int32()
                offset = r.int64()
                r.int32()  # partition max bytes
                wanted.append((pid, offset))
            out = (kp.enc_int32(0)  # throttle
                   + kp.enc_int32(1) + kp.enc_string(topic)
                   + kp.enc_int32(len(wanted)))
            for pid, offset in wanted:
                err = 0
                if self.force_error and self.force_error[0] == pid:
                    err = self.force_error[1]
                log = self.logs[topic][pid]
                chunk = log[offset:offset + 100]
                records = kp.encode_record_batch(chunk, base_offset=offset) \
                    if chunk and not err else b""
                out += (kp.enc_int32(pid) + kp.enc_int16(err)
                        + kp.enc_int64(len(log)) + kp.enc_int64(len(log))
                        + kp.enc_int32(0)  # aborted txns
                        + kp.enc_bytes(records))
            return out
        if api_key == kp.API_PRODUCE:
            r.string()  # transactional id
            r.int16()   # acks
            r.int32()   # timeout
            r.int32()   # topic count (assume 1)
            topic = r.string()
            r.int32()   # partition count (assume 1)
            pid = r.int32()
            batch = r.bytes_()
            log = self.logs[topic][pid]
            base = len(log)
            for _off, key, value in kp.parse_record_batches(batch):
                log.append((key, value))
            return (kp.enc_int32(1) + kp.enc_string(topic) + kp.enc_int32(1)
                    + kp.enc_int32(pid) + kp.enc_int16(0)
                    + kp.enc_int64(base) + kp.enc_int64(-1)
                    + kp.enc_int32(0))
        raise AssertionError(f"unhandled api {api_key}")

    def close(self):
        self.server.close()


def test_record_batch_roundtrip_and_crc():
    records = [(b"k1", b"v1"), (None, b"v2"), (b"k3", None)]
    blob = kp.encode_record_batch(records, base_offset=7)
    out = list(kp.parse_record_batches(blob))
    assert out == [(7, b"k1", b"v1"), (8, None, b"v2"), (9, b"k3", None)]
    # crc32c known-answer (Castagnoli of b'123456789' = 0xE3069283)
    assert kp.crc32c(b"123456789") == 0xE3069283
    # truncated tail is skipped, prefix survives
    two = kp.encode_record_batch([(b"a", b"1")]) + \
        kp.encode_record_batch([(b"b", b"2")])
    assert [v for _o, _k, v in kp.parse_record_batches(two[:-4])] == [b"1"]


def test_client_produce_fetch_roundtrip():
    broker = FakeBroker({"events": 2})
    try:
        c = kp.KafkaClient(f"127.0.0.1:{broker.port}")
        assert kp.API_FETCH in c.api_versions()
        assert c.metadata("events") == {0: 0, 1: 0}
        c.produce("events", 0, [(None, b"a"), (None, b"b")])
        c.produce("events", 1, [(None, b"c")])
        assert c.list_offsets("events", 0, -2) == 0
        assert c.list_offsets("events", 0, -1) == 2
        got = c.fetch("events", 0, 0)
        assert [v for _o, _k, v in got] == [b"a", b"b"]
        assert [o for o, _k, _v in got] == [0, 1]
        # fetch from mid-offset
        assert [v for _o, _k, v in c.fetch("events", 0, 1)] == [b"b"]
        c.close()
    finally:
        broker.close()


def test_kafka_connector_end_to_end_native():
    """pw.io.kafka write -> broker -> pw.io.kafka read, no kafka-python:
    the change stream round-trips with time/diff fields, across two
    partitions, with per-partition offset labels feeding the antichain."""
    broker = FakeBroker({"wordstream": 2})
    try:
        settings = {"bootstrap.servers": f"127.0.0.1:{broker.port}"}
        src = pw.debug.table_from_markdown("""
        word | n
        tpu  | 1
        mesh | 2
        slab | 3
        """)
        pw.io.kafka.write(src, settings, "wordstream", format="json")
        pw.run()
        total = sum(len(log) for log in broker.logs["wordstream"])
        assert total == 3

        G.clear()

        class S(pw.Schema):
            word: str
            n: int
            time: int
            diff: int

        t = pw.io.kafka.read(settings, topic="wordstream", schema=S,
                             format="json", autocommit_duration_ms=30)
        got = []
        pw.io.subscribe(t, on_change=lambda key, row, time, is_addition:
                        got.append(row["word"]))
        threading.Thread(target=lambda: pw.run(), daemon=True).start()
        deadline = time.time() + 10
        while time.time() < deadline and len(got) < 3:
            time.sleep(0.05)
        assert sorted(got) == ["mesh", "slab", "tpu"]
    finally:
        broker.close()


def test_varint_zigzag_edges():
    """Zigzag varints must roundtrip at the edges the record framing
    depends on (negative lengths = null key/value markers)."""
    for v in (0, -1, 1, -64, 63, 64, -65, 300, -300, 2**31 - 1,
              -(2**31), 2**40, -(2**40)):
        r = kp.Reader(kp.enc_varint(v))
        assert r.varint() == v, v


def test_record_batch_empty_and_single():
    assert list(kp.parse_record_batches(b"")) == []
    blob = kp.encode_record_batch([(None, None)])
    assert list(kp.parse_record_batches(blob)) == [(0, None, None)]


def test_control_batch_marker_distinct_from_tombstone():
    """A control batch's sentinel must NOT look like a (None, None)
    tombstone record — tombstones are real data (advisor r3 finding)."""
    blob = kp.encode_record_batch([(b"k", None)], base_offset=3)
    out = list(kp.parse_record_batches(blob))
    assert out == [(3, b"k", None)]  # tombstone: value None, not CONTROL
    assert all(v is not kp.CONTROL for _o, _k, v in out)


def test_offset_out_of_range_carries_partition():
    """fetch_many surfaces WHICH partition failed so the reader resets only
    that one (advisor r3 finding: a full reset re-emits healthy
    partitions under earliest / silently skips under latest)."""
    broker = FakeBroker({"t": 2})
    try:
        c = kp.KafkaClient(f"127.0.0.1:{broker.port}")
        c.produce("t", 0, [(None, b"a")])
        c.produce("t", 1, [(None, b"b")])
        broker.force_error = (1, 1)  # partition 1 -> OFFSET_OUT_OF_RANGE
        with pytest.raises(kp.KafkaProtocolError) as exc:
            c.fetch_many("t", {0: 0, 1: 5})
        assert exc.value.code == 1 and exc.value.partition == 1
        # healthy partition still fetches once the error clears
        broker.force_error = None
        got = c.fetch_many("t", {0: 0, 1: 0})
        assert [v for _o, _k, v in got[0]] == [b"a"]
        assert [v for _o, _k, v in got[1]] == [b"b"]
        c.close()
    finally:
        broker.close()
