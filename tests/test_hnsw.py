"""Native HNSW index (native/hnsw_index.cpp via ops/hnsw.py) — the real
USearchKnn backend (reference: usearch_integration.rs:20).

Pins: recall@10 >= 0.95 vs the exact scan, add/remove/upsert semantics,
metadata filters, save/load byte-buffer persistence, and the DataIndex
pipeline wiring."""

from __future__ import annotations

import numpy as np
import pytest

from pathway_tpu.internals.keys import Pointer
from pathway_tpu.ops.hnsw import HnswIndex
from pathway_tpu.ops.knn import KnnMetric

N, D = 8000, 32


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(3)
    data = rng.normal(size=(N, D)).astype(np.float32)
    index = HnswIndex(D, metric=KnnMetric.COS)
    for i in range(N):
        index.add(Pointer(i), data[i])
    return data, index


def test_recall_at_10_vs_exact(corpus):
    data, index = corpus
    rng = np.random.default_rng(11)
    queries = rng.normal(size=(50, D)).astype(np.float32)
    norms = np.linalg.norm(data, axis=1)
    res = index.search(
        [(Pointer(10**6 + i), queries[i], 10, None) for i in range(50)])
    hits = 0
    for i in range(50):
        sims = data @ queries[i] / (norms * np.linalg.norm(queries[i]))
        exact = set(np.argsort(-sims)[:10].tolist())
        hits += len({int(k) for k, _d in res[i]} & exact)
    recall = hits / 500
    assert recall >= 0.95, f"recall@10 = {recall}"


def test_distances_match_cosine_convention(corpus):
    data, index = corpus
    [matches] = index.search([(Pointer(10**6), data[5], 1, None)])
    key, dist = matches[0]
    assert key == Pointer(5) and dist < 1e-5  # self-match, 1 - cos = 0


def test_remove_and_upsert():
    rng = np.random.default_rng(1)
    data = rng.normal(size=(200, 16)).astype(np.float32)
    idx = HnswIndex(16, metric=KnnMetric.L2SQ)
    for i in range(200):
        idx.add(Pointer(i), data[i])
    assert len(idx) == 200
    idx.remove(Pointer(7))
    assert len(idx) == 199
    [m] = idx.search([(Pointer(999), data[7], 5, None)])
    assert Pointer(7) not in {k for k, _ in m}
    # upsert resurrects with the new vector
    idx.add(Pointer(7), data[8])
    [m2] = idx.search([(Pointer(999), data[8], 2, None)])
    assert {k for k, _ in m2} >= {Pointer(7), Pointer(8)}


def test_metadata_filter_escalates():
    rng = np.random.default_rng(2)
    data = rng.normal(size=(300, 16)).astype(np.float32)
    idx = HnswIndex(16, metric=KnnMetric.COS)
    for i in range(300):
        idx.add(Pointer(i), data[i],
                filter_data={"path": f"/{'even' if i % 2 == 0 else 'odd'}"})
    [m] = idx.search([
        (Pointer(999), data[0], 8, lambda d: d["path"] == "/odd")])
    assert len(m) == 8
    assert all(int(k) % 2 == 1 for k, _ in m)


def test_save_load_roundtrip(corpus):
    data, index = corpus
    blob = index.save_bytes()
    restored = HnswIndex.load_bytes(blob)
    assert len(restored) == len(index)
    q = data[17]
    [a] = index.search([(Pointer(999), q, 10, None)])
    [b] = restored.search([(Pointer(999), q, 10, None)])
    assert [int(k) for k, _ in a] == [int(k) for k, _ in b]


def test_usearch_knn_pipeline_uses_hnsw():
    """USearchKnn in a DataIndex pipeline is served by the native HNSW."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.ops.hnsw import HnswIndex as _H
    from pathway_tpu.stdlib.indexing import DataIndex
    from pathway_tpu.stdlib.indexing.nearest_neighbors import USearchKnn

    G.clear()
    rng = np.random.default_rng(5)
    vecs = rng.normal(size=(30, 8)).astype(np.float32)
    docs = table_from_rows(
        sch.schema_from_types(vec=np.ndarray, label=str),
        [(vecs[i], f"doc{i}") for i in range(30)])
    inner = USearchKnn(docs.vec, dimensions=8, metric="cos")
    assert isinstance(inner.factory().build(), _H)
    index = DataIndex(docs, inner)
    queries = table_from_rows(
        sch.schema_from_types(qvec=np.ndarray), [(vecs[3],)])
    res = index.query(queries.qvec, number_of_matches=1,
                      collapse_rows=False).select(label=pw.this.label)
    from pathway_tpu.internals.runner import run_tables

    [cap] = run_tables(res)
    labels = [r[0] for r in cap.snapshot().values()]
    assert labels == ["doc3"]
    G.clear()


def test_recall_survives_full_reembed_cycle():
    """Streaming updates (remove + re-add with a NEW vector, the engine's
    normal diff flow) must not erode recall: upserts relink the graph
    rather than patching vectors in place."""
    rng = np.random.default_rng(9)
    n, d = 3000, 24
    data = rng.normal(size=(n, d)).astype(np.float32)
    idx = HnswIndex(d, metric=KnnMetric.COS)
    for i in range(n):
        idx.add(Pointer(i), data[i])
    # re-embed every row (new random vectors), via remove+add
    data2 = rng.normal(size=(n, d)).astype(np.float32)
    for i in range(n):
        idx.remove(Pointer(i))
        idx.add(Pointer(i), data2[i])
    assert len(idx) == n
    queries = rng.normal(size=(30, d)).astype(np.float32)
    norms = np.linalg.norm(data2, axis=1)
    res = idx.search(
        [(Pointer(10**6 + i), queries[i], 10, None) for i in range(30)])
    hits = 0
    for i in range(30):
        sims = data2 @ queries[i] / (norms * np.linalg.norm(queries[i]))
        exact = set(np.argsort(-sims)[:10].tolist())
        hits += len({int(k) for k, _d in res[i]} & exact)
    recall = hits / 300
    assert recall >= 0.95, f"post-reembed recall@10 = {recall}"


def test_load_rejects_truncated_blob(corpus):
    _data, index = corpus
    blob = index.save_bytes()
    for cut in (len(blob) // 2, len(blob) - 5, 60):
        with pytest.raises(RuntimeError):
            HnswIndex.load_bytes(blob[:cut])


def test_load_rejects_tampered_graph_fields(corpus):
    """Bit-flipped graph fields (entry, link targets) must be rejected at
    load, not crash at search (structural validation in hnsw_load)."""
    _data, index = corpus
    blob = bytearray(index.save_bytes())
    side_len = int.from_bytes(blob[:8], "little")
    graph_off = 8 + side_len
    # entry field lives at graph offset 24 (magic, ver, dim, metric, M, efc)
    tampered = bytearray(blob)
    tampered[graph_off + 24:graph_off + 28] = (2**31 - 1).to_bytes(
        4, "little", signed=False)
    with pytest.raises(RuntimeError):
        HnswIndex.load_bytes(bytes(tampered))


def test_persisted_blob_contains_no_pickle(corpus):
    """Index files are untrusted input: the side channel is JSON, never
    pickle (loading must not be able to execute code)."""
    _data, index = corpus
    blob = index.save_bytes()
    side_len = int.from_bytes(blob[:8], "little")
    import json

    side = json.loads(blob[8:8 + side_len])
    assert set(side) >= {"keys", "dim", "metric"}
