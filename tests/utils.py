"""Test harness (reference: python/pathway/tests/utils.py:412-520 —
assert_table_equality & friends over captured diff streams)."""

from __future__ import annotations

from dataclasses import dataclass, field

from pathway_tpu.debug import table_from_markdown
from pathway_tpu.engine.delta import row_fingerprint
from pathway_tpu.internals.keys import Pointer
from pathway_tpu.internals.runner import run_tables

T = table_from_markdown


def _snapshot(table):
    [cap] = run_tables(table)
    return cap.snapshot()


def _assert_same_dtypes(actual, expected):
    """Column dtype comparison (reference: assert_table_equality checks
    types, the _wo_types variants don't — tests/utils.py:412). Catches
    silent dtype drift (int column widened to float) that row-value
    equality alone cannot see."""
    da = {n: repr(d) for n, d in actual.schema._dtypes().items()}
    de = {n: repr(d) for n, d in expected.schema._dtypes().items()}
    assert da == de, f"\nactual dtypes:   {da}\nexpected dtypes: {de}"


def assert_table_equality(actual, expected):
    """Same keys, same rows, same column dtypes."""
    _assert_same_dtypes(actual, expected)
    assert_table_equality_wo_types(actual, expected)


def assert_table_equality_wo_types(actual, expected):
    """Same keys, same rows (dtypes NOT compared)."""
    a, e = run_tables(actual, expected)
    sa, se = a.snapshot(), e.snapshot()
    assert _normalize(sa) == _normalize(se), f"\nactual:   {sa}\nexpected: {se}"


def assert_table_equality_wo_index(actual, expected):
    """Same multiset of rows and same dtypes, ignoring keys."""
    _assert_same_dtypes(actual, expected)
    assert_table_equality_wo_index_types(actual, expected)


def assert_table_equality_wo_index_types(actual, expected):
    """Same multiset of rows, ignoring keys (dtypes NOT compared)."""
    a, e = run_tables(actual, expected)
    ra = sorted((row_fingerprint(r) for r in a.snapshot().values()))
    re_ = sorted((row_fingerprint(r) for r in e.snapshot().values()))
    assert ra == re_, (
        f"\nactual rows:   {sorted(map(repr, a.snapshot().values()))}"
        f"\nexpected rows: {sorted(map(repr, e.snapshot().values()))}"
    )


def assert_stream_equality_wo_index(actual, expected):
    """Same consolidated (row, time, diff) stream, ignoring keys."""
    a, e = run_tables(actual, expected)
    ka = sorted((row_fingerprint(r), t, d) for _, r, t, d in a.consolidated_events())
    ke = sorted((row_fingerprint(r), t, d) for _, r, t, d in e.consolidated_events())
    assert ka == ke, (
        f"\nactual:   {sorted((r, t, d) for _, r, t, d in a.consolidated_events())}"
        f"\nexpected: {sorted((r, t, d) for _, r, t, d in e.consolidated_events())}"
    )


def assert_stream_equality(actual, expected):
    a, e = run_tables(actual, expected)
    ka = sorted((k, row_fingerprint(r), t, d)
                for k, r, t, d in a.consolidated_events())
    ke = sorted((k, row_fingerprint(r), t, d)
                for k, r, t, d in e.consolidated_events())
    assert ka == ke


def _normalize(snapshot):
    return {k: row_fingerprint(r) for k, r in snapshot.items()}


def rows_of(table) -> list[tuple]:
    return sorted(_snapshot(table).values(), key=repr)


@dataclass(order=True)
class DiffEntry:
    """One expected (key, order, insertion, row) event of an update
    stream (reference: tests/utils.py:97 DiffEntry). ``order`` ranks the
    expected events per key — engine times need not match it, only the
    per-key ordering."""

    key: Pointer
    order: int
    insertion: bool
    row: dict = field(compare=False)

    @staticmethod
    def create(pk_values: dict, order: int, insertion: bool,
               row: dict, instance=None) -> "DiffEntry":
        return DiffEntry(
            DiffEntry.create_id_from(pk_values, instance=instance),
            order, insertion, row)

    @staticmethod
    def create_id_from(pk_values: dict, instance=None) -> Pointer:
        from pathway_tpu.internals.keys import hash_values

        vals = list(pk_values.values())
        if instance is None:
            return hash_values(*vals)
        # instance-grouped outputs append the instance LAST to the key
        # hash (expression_compiler group-key compilation)
        return hash_values(*vals, instance)

    def final_cleanup_entry(self) -> "DiffEntry":
        return DiffEntry(self.key, self.order + 1, False, self.row)


def assert_key_entries_in_stream_consistent(expected: list[DiffEntry],
                                            table) -> None:
    """For every key: the table's update stream must be a SUBSEQUENCE of
    the expected per-key (order, insertion) sequence, ending on the same
    final entry (reference: tests/utils.py:210). Use for temporal
    behaviors where intermediate flushes may or may not surface."""
    import collections

    names = table.column_names()
    [cap] = run_tables(table)
    state: dict[Pointer, collections.deque] = collections.defaultdict(
        collections.deque)
    for entry in sorted(expected):
        state[entry.key].append(entry)
    for key, row, time, diff in cap.events:
        row_dict = dict(zip(names, row))
        q = state.get(key)
        assert q, (f"unexpected entry key={key!r} row={row_dict!r} "
                   f"diff={diff} (no expected entries left)")
        while True:
            entry = q.popleft()
            if (diff > 0, row_dict) == (entry.insertion, entry.row):
                if not q:
                    state.pop(key)
                break
            assert q, (f"entry key={key!r} row={row_dict!r} diff={diff} "
                       f"matches nothing expected for this key")
    assert not state, f"expected entries never observed: {dict(state)!r}"


def assert_stream_equal(expected: list[DiffEntry], table) -> None:
    """Exact per-key stream equality: every expected entry must appear,
    in order, with nothing skipped (reference: tests/utils.py:189)."""
    import collections

    names = table.column_names()
    [cap] = run_tables(table)
    state: dict[Pointer, collections.deque] = collections.defaultdict(
        collections.deque)
    for entry in sorted(expected):
        state[entry.key].append(entry)
    for key, row, time, diff in cap.events:
        row_dict = dict(zip(names, row))
        q = state.get(key)
        assert q, f"unexpected entry key={key!r} row={row_dict!r}"
        entry = q.popleft()
        assert (diff > 0, row_dict) == (entry.insertion, entry.row), (
            f"got key={key!r} row={row_dict!r} diff={diff}, expected "
            f"insertion={entry.insertion} row={entry.row!r}")
        if not q:
            state.pop(key)
    assert not state, f"expected entries never observed: {dict(state)!r}"


class CsvLinesNumberChecker:
    """Polling predicate: the CSV at ``path`` has ``n_lines`` data rows
    (reference: tests/utils.py CsvLinesNumberChecker — used to await
    streaming output files)."""

    def __init__(self, path, n_lines: int):
        self.path = path
        self.n_lines = n_lines

    def __call__(self) -> bool:
        import csv

        try:
            with open(self.path, newline="") as f:
                rows = sum(1 for _ in csv.reader(f)) - 1  # minus header
        except FileNotFoundError:
            return False
        return rows >= self.n_lines


def wait_result_with_checker(checker, timeout: float, *,
                             step: float = 0.1) -> bool:
    """Poll ``checker()`` until truthy or ``timeout`` seconds elapse
    (reference: tests/utils.py wait_result_with_checker, minus the
    process management — spawn-based tests manage their own processes)."""
    import time as _time

    deadline = _time.time() + timeout
    while _time.time() < deadline:
        if checker():
            return True
        _time.sleep(step)
    return bool(checker())
