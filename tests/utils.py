"""Test harness (reference: python/pathway/tests/utils.py:412-520 —
assert_table_equality & friends over captured diff streams)."""

from __future__ import annotations

from pathway_tpu.debug import table_from_markdown
from pathway_tpu.engine.delta import row_fingerprint
from pathway_tpu.internals.runner import run_tables

T = table_from_markdown


def _snapshot(table):
    [cap] = run_tables(table)
    return cap.snapshot()


def assert_table_equality(actual, expected):
    """Same keys, same rows."""
    a, e = run_tables(actual, expected)
    sa, se = a.snapshot(), e.snapshot()
    assert _normalize(sa) == _normalize(se), f"\nactual:   {sa}\nexpected: {se}"


def assert_table_equality_wo_index(actual, expected):
    """Same multiset of rows, ignoring keys."""
    a, e = run_tables(actual, expected)
    ra = sorted((row_fingerprint(r) for r in a.snapshot().values()))
    re_ = sorted((row_fingerprint(r) for r in e.snapshot().values()))
    assert ra == re_, (
        f"\nactual rows:   {sorted(map(repr, a.snapshot().values()))}"
        f"\nexpected rows: {sorted(map(repr, e.snapshot().values()))}"
    )


assert_table_equality_wo_types = assert_table_equality
assert_table_equality_wo_index_types = assert_table_equality_wo_index


def assert_stream_equality_wo_index(actual, expected):
    """Same consolidated (row, time, diff) stream, ignoring keys."""
    a, e = run_tables(actual, expected)
    ka = sorted((row_fingerprint(r), t, d) for _, r, t, d in a.consolidated_events())
    ke = sorted((row_fingerprint(r), t, d) for _, r, t, d in e.consolidated_events())
    assert ka == ke, (
        f"\nactual:   {sorted((r, t, d) for _, r, t, d in a.consolidated_events())}"
        f"\nexpected: {sorted((r, t, d) for _, r, t, d in e.consolidated_events())}"
    )


def assert_stream_equality(actual, expected):
    a, e = run_tables(actual, expected)
    ka = sorted((k, row_fingerprint(r), t, d)
                for k, r, t, d in a.consolidated_events())
    ke = sorted((k, row_fingerprint(r), t, d)
                for k, r, t, d in e.consolidated_events())
    assert ka == ke


def _normalize(snapshot):
    return {k: row_fingerprint(r) for k, r in snapshot.items()}


def rows_of(table) -> list[tuple]:
    return sorted(_snapshot(table).values(), key=repr)
