"""Joins (reference: join_tables, src/engine/dataflow.rs:2276)."""

import pathway_tpu as pw
from tests.utils import T, assert_table_equality_wo_index, rows_of


def _sides():
    t1 = T("""
    k | v
    1 | a
    2 | b
    3 | c
    """)
    t2 = T("""
    k | w
    2 | X
    3 | Y
    4 | Z
    """)
    return t1, t2


def test_inner():
    t1, t2 = _sides()
    r = t1.join(t2, t1.k == t2.k).select(t1.v, t2.w)
    assert sorted(rows_of(r)) == [("b", "X"), ("c", "Y")]


def test_left():
    t1, t2 = _sides()
    r = t1.join_left(t2, t1.k == t2.k).select(t1.v, w=t2.w)
    assert sorted(rows_of(r), key=repr) == [("a", None), ("b", "X"), ("c", "Y")]


def test_right():
    t1, t2 = _sides()
    r = t1.join_right(t2, t1.k == t2.k).select(v=t1.v, w=t2.w)
    assert sorted(rows_of(r), key=str) == [("b", "X"), ("c", "Y"), (None, "Z")]


def test_outer():
    t1, t2 = _sides()
    r = t1.join_outer(t2, t1.k == t2.k).select(v=t1.v, w=t2.w)
    assert len(rows_of(r)) == 4


def test_left_right_this_syntax():
    t1, t2 = _sides()
    r = t1.join(t2, pw.left.k == pw.right.k).select(pw.left.v, pw.right.w)
    assert sorted(rows_of(r)) == [("b", "X"), ("c", "Y")]


def test_join_id_left():
    t1, t2 = _sides()
    r = t1.join(t2, t1.k == t2.k, id=t1.id).select(t1.v, t2.w)
    # keeping left ids: can update_cells back onto t1's subuniverse
    assert sorted(rows_of(r)) == [("b", "X"), ("c", "Y")]


def test_multi_condition():
    t1 = T("""
    a | b | v
    1 | 1 | p
    1 | 2 | q
    """)
    t2 = T("""
    a | b | w
    1 | 2 | r
    """)
    r = t1.join(t2, t1.a == t2.a, t1.b == t2.b).select(t1.v, t2.w)
    assert rows_of(r) == [("q", "r")]


def test_join_expressions_in_select():
    t1, t2 = _sides()
    r = t1.join(t2, t1.k == t2.k).select(z=t1.k * 10 + t2.k)
    assert sorted(rows_of(r)) == [(22,), (33,)]


def test_incremental_join_retraction():
    t1 = T("""
    k | v | _time | _diff
    1 | a | 2     | 1
    1 | a | 6     | -1
    """)
    t2 = T("""
    k | w | _time
    1 | X | 4
    """)
    r = t1.join(t2, t1.k == t2.k).select(t1.v, t2.w)
    assert rows_of(r) == []


def test_bilinear_join_matches_recompute_on_malformed_upserts():
    """The bilinear delta path must mirror the per-group recompute path's
    dict semantics even for streams that break the retract-then-insert
    contract: an insert over a live key is an upsert (old outputs
    retracted), a duplicate identical insert is a no-op, and a retraction
    of an absent row emits nothing."""
    import random

    from pathway_tpu.engine.delta import Delta, row_fingerprint
    from pathway_tpu.engine.operators import JoinOperator
    from pathway_tpu.internals.keys import Pointer

    def run(mode, entries_seq, bilinear):
        op = JoinOperator(
            mode,
            lambda k, r: r[0], lambda k, r: r[0],
            lambda lk, lr, rk, rr: ((lr or (None, None))[1],
                                    (rr or (None, None))[1]))
        op._bilinear = bilinear
        acc: dict = {}
        for dl_entries, dr_entries in entries_seq:
            out = op.step(0, [Delta(list(dl_entries)),
                              Delta(list(dr_entries))])
            for k, row, d in out.entries:
                fp = (int(k), row_fingerprint(row))
                acc[fp] = acc.get(fp, 0) + d
        return {k: v for k, v in acc.items() if v}

    rng = random.Random(7)
    keys = [Pointer(i) for i in range(6)]
    for mode in ("inner", "left", "right", "outer"):
        for trial in range(20):
            seq = []
            for _tick in range(6):
                dl = [(rng.choice(keys), (f"j{rng.randrange(3)}",
                                          f"l{rng.randrange(4)}"),
                       rng.choice((1, 1, -1)))
                      for _ in range(rng.randrange(4))]
                dr = [(rng.choice(keys), (f"j{rng.randrange(3)}",
                                          f"r{rng.randrange(4)}"),
                       rng.choice((1, 1, -1)))
                      for _ in range(rng.randrange(4))]
                seq.append((dl, dr))
            fast = run(mode, seq, True)
            slow = run(mode, seq, False)
            assert fast == slow, (mode, trial, fast, slow)
