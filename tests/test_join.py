"""Joins (reference: join_tables, src/engine/dataflow.rs:2276)."""

import pathway_tpu as pw
from tests.utils import T, assert_table_equality_wo_index, rows_of


def _sides():
    t1 = T("""
    k | v
    1 | a
    2 | b
    3 | c
    """)
    t2 = T("""
    k | w
    2 | X
    3 | Y
    4 | Z
    """)
    return t1, t2


def test_inner():
    t1, t2 = _sides()
    r = t1.join(t2, t1.k == t2.k).select(t1.v, t2.w)
    assert sorted(rows_of(r)) == [("b", "X"), ("c", "Y")]


def test_left():
    t1, t2 = _sides()
    r = t1.join_left(t2, t1.k == t2.k).select(t1.v, w=t2.w)
    assert sorted(rows_of(r), key=repr) == [("a", None), ("b", "X"), ("c", "Y")]


def test_right():
    t1, t2 = _sides()
    r = t1.join_right(t2, t1.k == t2.k).select(v=t1.v, w=t2.w)
    assert sorted(rows_of(r), key=str) == [("b", "X"), ("c", "Y"), (None, "Z")]


def test_outer():
    t1, t2 = _sides()
    r = t1.join_outer(t2, t1.k == t2.k).select(v=t1.v, w=t2.w)
    assert len(rows_of(r)) == 4


def test_left_right_this_syntax():
    t1, t2 = _sides()
    r = t1.join(t2, pw.left.k == pw.right.k).select(pw.left.v, pw.right.w)
    assert sorted(rows_of(r)) == [("b", "X"), ("c", "Y")]


def test_join_id_left():
    t1, t2 = _sides()
    r = t1.join(t2, t1.k == t2.k, id=t1.id).select(t1.v, t2.w)
    # keeping left ids: can update_cells back onto t1's subuniverse
    assert sorted(rows_of(r)) == [("b", "X"), ("c", "Y")]


def test_multi_condition():
    t1 = T("""
    a | b | v
    1 | 1 | p
    1 | 2 | q
    """)
    t2 = T("""
    a | b | w
    1 | 2 | r
    """)
    r = t1.join(t2, t1.a == t2.a, t1.b == t2.b).select(t1.v, t2.w)
    assert rows_of(r) == [("q", "r")]


def test_join_expressions_in_select():
    t1, t2 = _sides()
    r = t1.join(t2, t1.k == t2.k).select(z=t1.k * 10 + t2.k)
    assert sorted(rows_of(r)) == [(22,), (33,)]


def test_incremental_join_retraction():
    t1 = T("""
    k | v | _time | _diff
    1 | a | 2     | 1
    1 | a | 6     | -1
    """)
    t2 = T("""
    k | w | _time
    1 | X | 4
    """)
    r = t1.join(t2, t1.k == t2.k).select(t1.v, t2.w)
    assert rows_of(r) == []
