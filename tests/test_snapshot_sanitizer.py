"""Runtime snapshot-coverage sanitizer (engine/snapshot_sanitizer.py):
unit coverage for mutation tracing, coverage diffing, the exempt tuple,
report mode and the shadow restore round-trip — then end-to-end on a
real streaming graph: a seeded uncovered-attr mutation is caught at the
first snapshot, and a fully sanitized recovery run stays violation-free
with restored output byte-identical to the unsanitized baseline."""

from __future__ import annotations

import json

import pytest

import pathway_tpu as pw
from pathway_tpu.engine import snapshot_sanitizer as snapsan
from pathway_tpu.engine.operators import Operator
from pathway_tpu.engine.snapshot_sanitizer import (
    SnapshotCoverageViolation, checked_snapshot, track_operator, violations)
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import faults
from pathway_tpu.testing.faults import flaky_subject

WORDS = ["a", "b", "a", "c", "b", "a"]


@pytest.fixture(autouse=True)
def _fresh():
    G.clear()
    faults.reset()
    snapsan._reset_for_tests()
    yield
    G.clear()
    faults.reset()
    snapsan._reset_for_tests()


# ---------------------------------------------------------------------------
# toy operators
# ---------------------------------------------------------------------------

class LeakyOperator(Operator):
    """Mutates ``scratch`` that ``snapshot_state`` never captures."""

    def __init__(self):
        self.counts: dict = {}
        self.scratch: dict = {}

    def snapshot_state(self):
        return {"counts": dict(self.counts)}

    def restore_state(self, state) -> None:
        self.counts = dict(state["counts"])


class LossyOperator(Operator):
    """Captures two keys; restore resets one — not a fixed point.

    (A restore that leaves ``b`` *untouched* is invisible to the shadow
    round-trip — the shadow starts from the live instance — which is why
    the static PWT302 key-asymmetry check exists alongside this.)"""

    def __init__(self):
        self.a: dict = {}
        self.b: dict = {}

    def snapshot_state(self):
        return {"a": dict(self.a), "b": dict(self.b)}

    def restore_state(self, state) -> None:
        self.a = dict(state["a"])
        self.b = {}  # captured "b" discarded


class StatelessOperator(Operator):
    """No snapshot_state override — outside the snapshot protocol."""


# ---------------------------------------------------------------------------
# unit: tracking + coverage diff
# ---------------------------------------------------------------------------

def test_uncovered_inplace_mutation_raises():
    op = track_operator(LeakyOperator())
    op.counts["a"] = 1   # covered: snapshot_state reads self.counts
    op.scratch["x"] = 1  # in-place, never captured
    with pytest.raises(SnapshotCoverageViolation) as e:
        checked_snapshot(op)
    assert "'scratch'" in str(e.value)
    assert "LeakyOperator" in str(e.value)
    assert "_snapshot_sanitizer_exempt" in str(e.value)


def test_uncovered_rebind_names_the_write_site():
    op = track_operator(LeakyOperator())
    op.scratch = {"x": 1}  # rebind goes through the __setattr__ tracer
    with pytest.raises(SnapshotCoverageViolation) as e:
        checked_snapshot(op)
    assert "test_snapshot_sanitizer.py" in str(e.value)


def test_covered_mutation_is_clean_and_round_trips():
    op = track_operator(LeakyOperator())
    op.counts["a"] = 1
    assert checked_snapshot(op) == {"counts": {"a": 1}}
    assert violations() == []
    # baselines reset at each snapshot: a fresh covered mutation is
    # clean again, an old one does not re-fire
    op.counts["b"] = 2
    assert checked_snapshot(op) == {"counts": {"a": 1, "b": 2}}
    assert violations() == []


def test_exempt_tuple_suppresses_scratch_attr():
    class ExemptOperator(LeakyOperator):
        _snapshot_sanitizer_exempt = ("scratch",)

    op = track_operator(ExemptOperator())
    op.scratch["x"] = 1
    checked_snapshot(op)
    assert violations() == []


def test_stateless_operator_is_not_tracked():
    op = StatelessOperator()
    assert track_operator(op) is op
    assert type(op) is StatelessOperator  # class swap skipped


def test_traced_class_is_indistinguishable():
    # graph_fingerprint() keys node identity on type(op).__name__
    op = track_operator(LeakyOperator())
    assert type(op).__name__ == "LeakyOperator"
    assert type(op).__qualname__ == LeakyOperator.__qualname__
    assert isinstance(op, LeakyOperator)


def test_untracked_operator_passes_through():
    op = LeakyOperator()  # never tracked
    op.scratch["x"] = 1
    assert checked_snapshot(op) == {"counts": {}}
    assert violations() == []


def test_report_mode_records_without_raising(monkeypatch):
    monkeypatch.setenv("PATHWAY_SNAPSHOT_SANITIZER", "report")
    op = track_operator(LeakyOperator())
    op.scratch["x"] = 1
    assert checked_snapshot(op) == {"counts": {}}
    assert len(violations()) == 1
    assert "'scratch'" in violations()[0]["message"]


def test_reset_for_tests_clears_log():
    op = track_operator(LeakyOperator())
    op.scratch["x"] = 1
    with pytest.raises(SnapshotCoverageViolation):
        checked_snapshot(op)
    assert violations()
    snapsan._reset_for_tests()
    assert violations() == []


# ---------------------------------------------------------------------------
# unit: shadow round-trip
# ---------------------------------------------------------------------------

def test_lossy_restore_is_not_a_fixed_point():
    op = track_operator(LossyOperator())
    op.a["k"] = 1
    op.b["k"] = 2
    with pytest.raises(SnapshotCoverageViolation) as e:
        checked_snapshot(op)
    assert "not a fixed point" in str(e.value)
    assert "PWT302" in str(e.value)  # points at the static twin


def test_unpicklable_state_is_a_violation():
    class CallableStateOperator(LeakyOperator):
        def snapshot_state(self):
            return {"counts": dict(self.counts), "fn": lambda: None}

    op = track_operator(CallableStateOperator())
    with pytest.raises(SnapshotCoverageViolation) as e:
        checked_snapshot(op)
    assert "not picklable" in str(e.value)


class _Opaque:  # picklable (module-level) but not in _SAFE_GLOBALS
    pass


def test_non_whitelisted_state_type_is_a_violation():
    class OpaqueStateOperator(LeakyOperator):
        def snapshot_state(self):
            return {"counts": dict(self.counts), "blob": _Opaque()}

    op = track_operator(OpaqueStateOperator())
    with pytest.raises(SnapshotCoverageViolation) as e:
        checked_snapshot(op)
    assert "restricted unpickler" in str(e.value)


# ---------------------------------------------------------------------------
# end-to-end on a real streaming graph
# ---------------------------------------------------------------------------

def _rows(words):
    return [{"word": w} for w in words]


def _run_wordcount(subject, *, backend=None):
    G.clear()
    t = pw.io.python.read(
        subject, schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=10, persistent_id="sanitizer-words")
    counts = t.groupby(t.word).reduce(word=t.word, c=pw.reducers.count())
    state: dict[str, int] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["word"]] = row["c"]
        elif state.get(row["word"]) == row["c"]:
            del state[row["word"]]

    pw.io.subscribe(counts, on_change)
    cfg = None
    if backend is not None:
        cfg = pw.persistence.Config.simple_config(backend)
    pw.run(persistence_config=cfg)
    return state


def _as_bytes(state: dict) -> bytes:
    return json.dumps(sorted(state.items())).encode()


def test_e2e_seeded_uncovered_mutation_is_caught(monkeypatch, tmp_path):
    """A groupby operator leaking per-step state into an attr its
    snapshot never captures dies at the first snapshot pass, not as
    silently wrong answers after a future recovery."""
    from pathway_tpu.engine.operators import (ColumnarGroupByOperator,
                                              GroupByOperator)

    for cls in (ColumnarGroupByOperator, GroupByOperator):
        orig_init = cls.__init__
        orig_step = cls.step

        def patched_init(self, *a, __orig=orig_init, **k):
            __orig(self, *a, **k)
            self._leak = {}

        def patched_step(self, time, in_deltas, __orig=orig_step):
            self._leak[time] = time  # uncovered in-place mutation
            return __orig(self, time, in_deltas)

        monkeypatch.setattr(cls, "__init__", patched_init)
        monkeypatch.setattr(cls, "step", patched_step)

    monkeypatch.setenv("PATHWAY_SNAPSHOT_SANITIZER", "1")
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    with pytest.raises(SnapshotCoverageViolation) as e:
        _run_wordcount(flaky_subject(_rows(WORDS), fail_after=0,
                                     fail_attempts=0, delay_s=0.02),
                       backend=backend)
    assert "'_leak'" in str(e.value)


def test_e2e_sanitized_recovery_is_clean_and_byte_identical(monkeypatch,
                                                            tmp_path):
    """The acceptance run: full recovery cycle under the live sanitizer
    — zero violations, restored output byte-identical to the
    unsanitized baseline."""
    baseline = _run_wordcount(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0))
    assert baseline == {"a": 3, "b": 2, "c": 1}

    monkeypatch.setenv("PATHWAY_SNAPSHOT_SANITIZER", "1")
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    first = _run_wordcount(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                      delay_s=0.02), backend=backend)
    assert _as_bytes(first) == _as_bytes(baseline)
    restored = _run_wordcount(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        backend=backend)
    assert _as_bytes(restored) == _as_bytes(baseline)
    assert violations() == []
