"""Declarative YAML pipeline loader (reference:
python/pathway/internals/yaml_loader.py — $variables, !pw object tags,
env interpolation)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.yaml_loader import load_yaml


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


def test_variables_and_env_interpolation(monkeypatch):
    monkeypatch.setenv("PW_TEST_DIR", "/data/in")
    cfg = load_yaml("""
$root: ${PW_TEST_DIR}
$k: 7
input_dir: $root
top_k: $k
plain: value
""")
    assert cfg == {"input_dir": "/data/in", "top_k": 7, "plain": "value"}


def test_pw_tags_instantiate_objects():
    cfg = load_yaml("""
splitter: !pw.xpacks.llm.splitters.TokenCountSplitter
  min_tokens: 10
  max_tokens: 100
parser: !pw.xpacks.llm.parsers.ParseUtf8 {}
""")
    from pathway_tpu.xpacks.llm.parsers import ParseUtf8
    from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter

    assert isinstance(cfg["splitter"], TokenCountSplitter)
    assert cfg["splitter"].max_tokens == 100
    assert isinstance(cfg["parser"], ParseUtf8)


def test_pw_tag_with_variable_argument():
    cfg = load_yaml("""
$dim: 16
index: !pw.stdlib.indexing.BruteForceKnnFactory
  dimensions: $dim
  reserved_space: 32
""")
    factory = cfg["index"]
    assert factory.dimensions == 16 and factory.reserved_space == 32


def test_declarative_pipeline_runs(tmp_path):
    """A whole pipeline declared in YAML: source -> select -> output —
    the loader feeds the same objects the Python API would build."""
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "a.txt").write_text("hello\nworld\n")
    cfg = load_yaml(f"""
$input: {tmp_path}/in
source: !pw.io.fs.read
  path: $input
  format: plaintext
  mode: static
""")
    t = cfg["source"]
    out = t.select(upper=pw.apply(str.upper, t.data))
    rows = sorted(r[0] for r in
                  pw.debug.table_to_pandas(out).itertuples(index=False))
    assert rows == ["HELLO", "WORLD"]
