"""Replica-fleet canary: snapshot-hydrated read replicas behind the
latency-aware router must survive replica death and get faster when the
fleet grows — proven on a REAL multi-process fleet, not mocks.

Drives ``bench.bench_replica()`` (engine/replica.py + engine/router.py):
a primary and read replicas run as separate OS processes (each a full
``pw.run`` — the replicas with ``replica_of=`` hydrating from the
primary's snapshot generation + WAL suffix and registering over the
framed HMAC control channel), fronted by the in-process QueryRouter,
under closed-loop query load from client threads. Gates:

1. **failover** — SIGKILL one replica mid-window under live load: ZERO
   lost queries end to end (the router holds each body and replays it on
   the next-best replica), >= 1 failover actually observed (the gate saw
   a real death, not a quiet window), and the router dropped the corpse
   from the fleet;
2. **elasticity** — adding a second replica drops the front-door p95
   (ratio gated <= REPLICA_P95_RATIO, default 0.9; the per-query cost is
   a sleep — wall-clock, not cores — so the drop is honest on 1-core
   runners) and the load genuinely spreads (both replicas served);
3. **staleness exposition** — per-replica
   ``pathway_tpu_replica_staleness_ticks{replica=}`` scraped from the
   router's real /metrics HTTP surface during the run;
4. **bounded hydration** — replica time-to-ready from snapshot+suffix
   stays ~flat across history sizes (<= REPLICA_READY_RATIO, default
   2.0, largest vs smallest — the WAL-only contrast is reported, not
   gated: it is the linear baseline).

The leg's JSON is written as a CI artifact AND checkpointed into
``BENCH_LASTGOOD.json`` per the evidence rule.

Exits 0 iff all hold. Run: ``python tests/replica_canary.py``.
Knobs: BENCH_REPLICA_ROWS, BENCH_REPLICA_LOAD_S, BENCH_REPLICA_CLIENTS,
REPLICA_P95_RATIO, REPLICA_READY_RATIO, REPLICA_BENCH_ARTIFACT (JSON
path), BENCH_LASTGOOD_PATH.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

P95_RATIO = float(os.environ.get("REPLICA_P95_RATIO", 0.9))
READY_RATIO = float(os.environ.get("REPLICA_READY_RATIO", 2.0))


def main() -> int:
    import bench

    out = bench.bench_replica()
    bench._write_lastgood(out)  # evidence rule: checkpoint immediately
    artifact = os.environ.get("REPLICA_BENCH_ARTIFACT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump(out, f, indent=1)

    # gate 1: failover — a SIGKILLed replica under live load costs
    # retries, never queries
    assert out["replica_kill_queries"] > 0, out
    assert out["replica_lost_queries"] == 0, (
        f"{out['replica_lost_queries']} of {out['replica_kill_queries']} "
        "queries lost across the replica kill — failover leaked load")
    assert out["replica_failovers"] >= 1, (
        "no failover observed: the kill window never exercised the "
        "replay path, the zero-lost gate proved nothing")
    assert out["replica_fleet_after_kill"] == ["r2"], (
        f"router still routes to the corpse: "
        f"{out['replica_fleet_after_kill']}")
    print(f"[gate1] {out['replica_kill_queries']} queries across the "
          f"SIGKILL, 0 lost, {out['replica_failovers']} failover(s), "
          f"fleet converged to {out['replica_fleet_after_kill']}")

    # gate 2: elasticity — the second replica must demonstrably drop p95
    # and actually take traffic
    ratio = out.get("replica_p95_ratio_2v1")
    assert ratio is not None, f"no p95 measured in a load phase: {out}"
    assert ratio <= P95_RATIO, (
        f"p95 with 2 replicas is {ratio}x the 1-replica p95 "
        f"({out['replica_p95_ms_1']} -> {out['replica_p95_ms_2']} ms): "
        f"adding a replica did not demonstrably help (gate {P95_RATIO})")
    assert out["replica_requests_r1"] > 0 \
        and out["replica_requests_r2"] > 0, (
        f"load did not spread: r1={out['replica_requests_r1']} "
        f"r2={out['replica_requests_r2']} in the 2-replica window")
    print(f"[gate2] p95 {out['replica_p95_ms_1']} -> "
          f"{out['replica_p95_ms_2']} ms ({ratio}x <= {P95_RATIO}) "
          f"with spread r1={out['replica_requests_r1']} "
          f"r2={out['replica_requests_r2']}")

    # gate 3: per-replica staleness exported on the router's real
    # /metrics surface (scraped over HTTP during the run)
    assert out["replica_staleness_exported"] is True, (
        "pathway_tpu_replica_staleness_ticks{replica=} missing from the "
        "router's /metrics")
    print(f"[gate3] staleness exported per replica (max lag observed: "
          f"{out['replica_max_staleness_ticks']} ticks)")

    # gate 4: snapshot hydration bounded — time-to-ready ~flat vs
    # history size (the WAL-only numbers are the linear contrast)
    ready_ratio = out["replica_snapshot_ready_ratio_maxmin"]
    assert ready_ratio <= READY_RATIO, (
        f"snapshot-hydrated replica ready time not flat: {ready_ratio}x "
        f"largest-vs-smallest history (gate {READY_RATIO})")
    print(f"[gate4] snapshot-hydrated ready time ratio {ready_ratio} "
          f"<= {READY_RATIO} across history sizes")

    print("replica canary: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
