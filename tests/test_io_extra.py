"""pyfilesystem (fsspec-backed) connector + parquet fs format
(reference: python/pathway/io/pyfilesystem/__init__.py:142; parquet ~
DeltaTableWriter's columnar sink, data_storage.rs:2687)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from tests.utils import rows_of


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


def test_pyfilesystem_read_local(tmp_path):
    (tmp_path / "a.txt").write_bytes(b"alpha")
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "b.bin").write_bytes(b"\x00\x01beta")
    t = pw.io.pyfilesystem.read(f"file://{tmp_path}", mode="static",
                                with_metadata=True)
    got = sorted(rows_of(t), key=lambda r: r[0])
    assert [r[0] for r in got] == [b"\x00\x01beta", b"alpha"]
    metas = [r[1].value for r in got]
    assert metas[0]["path"].endswith("b.bin")
    assert metas[0]["size"] == 6


def test_pyfilesystem_read_memory_fs():
    import fsspec

    fs = fsspec.filesystem("memory")
    fs.pipe("/pwtest/x.txt", b"hello")
    fs.pipe("/pwtest/y.txt", b"world")
    try:
        t = pw.io.pyfilesystem.read(fs, path="/pwtest", mode="static")
        got = sorted(rows_of(t))
        assert got == [(b"hello",), (b"world",)]
    finally:
        fs.rm("/pwtest", recursive=True)


def test_pyfilesystem_streaming_picks_up_new_files(tmp_path):
    import threading
    import time

    (tmp_path / "a.txt").write_bytes(b"one")
    seen = []
    t = pw.io.pyfilesystem.read(f"file://{tmp_path}", mode="streaming",
                                refresh_interval=0.2)
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition:
                    seen.append((row["data"], is_addition)))

    def feed():
        time.sleep(1.0)
        (tmp_path / "b.txt").write_bytes(b"two")

    th = threading.Thread(target=feed, daemon=True)
    th.start()

    runner_th = threading.Thread(
        target=lambda: pw.run(), daemon=True)
    runner_th.start()
    deadline = time.time() + 10
    while time.time() < deadline:
        if {d for d, add in seen if add} == {b"one", b"two"}:
            break
        time.sleep(0.1)
    assert {d for d, add in seen if add} == {b"one", b"two"}


def test_parquet_write_read_roundtrip(tmp_path):
    t = pw.debug.table_from_markdown("""
    name  | qty
    alice | 3
    bob   | 5
    """)
    out = str(tmp_path / "out.parquet")
    pw.io.fs.write(t, out, format="parquet")
    pw.run()

    class S(pw.Schema):
        name: str
        qty: int
        time: int
        diff: int

    G.clear()
    back = pw.io.fs.read(out, format="parquet", schema=S, mode="static")
    got = sorted(rows_of(back))
    assert [(r[0], r[1], r[3]) for r in got] == [
        ("alice", 3, 1), ("bob", 5, 1)]


def test_s3_settings_and_gating():
    """AwsS3Settings/MinIOSettings plumbing is real; the s3 protocol gates
    at runtime on s3fs with a clear message."""
    s = pw.io.s3.AwsS3Settings(
        bucket_name="b", access_key="ak", secret_access_key="sk",
        endpoint="https://minio.local:9000", region="us-east-1")
    opts = s.storage_options()
    assert opts["key"] == "ak" and opts["secret"] == "sk"
    assert opts["client_kwargs"]["endpoint_url"] == "https://minio.local:9000"
    m = pw.io.minio.MinIOSettings(
        endpoint="minio.local:9000", bucket_name="b", access_key="ak",
        secret_access_key="sk")
    aws = m.create_aws_settings()
    assert aws.endpoint == "https://minio.local:9000"
    with pytest.raises(ImportError, match="s3fs"):
        pw.io.s3.read("s3://b/prefix", aws_s3_settings=s)


def test_elasticsearch_bulk_writer_local_double(tmp_path):
    """pw.io.elasticsearch posts real bulk NDJSON over HTTP — verified
    against an in-test server double (no client lib involved)."""
    import http.server
    import threading

    received = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            received.append((self.path, self.rfile.read(n).decode()))
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b'{"errors": false}')

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        t = pw.debug.table_from_markdown("""
        word | n
        a    | 1
        b    | 2
        """)
        pw.io.elasticsearch.write(
            t, f"http://127.0.0.1:{port}",
            pw.io.elasticsearch.ElasticSearchAuth.apikey("k"),
            index_name="idx")
        pw.run()
    finally:
        srv.shutdown()
    assert received, "no bulk request arrived"
    path, body = received[0]
    assert path == "/_bulk"
    import json

    lines = [json.loads(l) for l in body.strip().splitlines()]
    actions = [l for l in lines if "index" in l]
    docs = [l for l in lines if "word" in l]
    assert all(a["index"]["_index"] == "idx" for a in actions)
    assert sorted(d["word"] for d in docs) == ["a", "b"]
    assert all(d["diff"] == 1 for d in docs)


def test_slack_send_alerts_posts_messages(monkeypatch):
    calls = []

    class _Resp:
        def raise_for_status(self):
            pass

    def fake_post(url, headers=None, json=None, **kw):
        calls.append((url, headers, json))
        return _Resp()

    import requests

    monkeypatch.setattr(requests, "post", fake_post)
    t = pw.debug.table_from_markdown("""
    msg
    alert_one
    alert_two
    """)
    pw.io.slack.send_alerts(t.msg, "C123", "xoxb-token")
    pw.run()
    assert len(calls) == 2
    url, headers, payload = calls[0]
    assert url.endswith("chat.postMessage")
    assert headers["Authorization"] == "Bearer xoxb-token"
    assert {c[2]["text"] for c in calls} == {"alert_one", "alert_two"}
    assert all(c[2]["channel"] == "C123" for c in calls)


def test_redpanda_delegates_to_kafka():
    import pathway_tpu.io.kafka as k
    import pathway_tpu.io.redpanda as rp

    assert rp.read.__module__ == "pathway_tpu.io.redpanda"
    # same plumbing object underneath
    assert rp._kafka is k


def test_http_write_retries_and_logs(caplog, tmp_path):
    """http sink retries with backoff and logs final failures instead of
    silently dropping events (regression: bare except-pass)."""
    import http.server
    import logging
    import threading

    attempts = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            self.rfile.read(n)
            attempts.append(1)
            if len(attempts) < 2:  # first attempt fails, retry succeeds
                self.send_response(503)
            else:
                self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        t = pw.debug.table_from_markdown("msg\nhello")
        pw.io.logstash.write(t, f"http://127.0.0.1:{port}", n_retries=3,
                             retry_delay_s=0.05)
        pw.run()
        assert len(attempts) == 2  # 503 then success
        # unreachable endpoint → logged error, no exception
        G.clear()
        t2 = pw.debug.table_from_markdown("msg\nboom")
        pw.io.http.write(t2, "http://127.0.0.1:9/never", n_retries=1,
                         retry_delay_s=0.01)
        with caplog.at_level(logging.ERROR):
            pw.run()
        assert any("delivery failed after 2" in r.message
                   for r in caplog.records)
    finally:
        srv.shutdown()


def test_gradual_broadcast_insert_before_retract_update():
    """Regression: an update pair arriving insert-first must not drop the
    key from operator state."""
    from pathway_tpu.engine.delta import Delta
    from pathway_tpu.engine.operators import GradualBroadcastOperator
    from pathway_tpu.internals.keys import hash_values

    op = GradualBroadcastOperator()
    k = hash_values("row")
    tk = hash_values("thr")
    op.step(0, [Delta([(k, ("old",), 1)]),
                Delta([(tk, (0.0, 10.0, 10.0), 1)])])
    # update delivered insert-first (exchange merging can permute order)
    out = op.step(1, [Delta([(k, ("new",), 1), (k, ("old",), -1)]),
                      Delta()])
    state = {}
    for key, row, d in out.entries:
        state[row] = state.get(row, 0) + d
    live = {r for r, c in state.items() if c > 0}
    assert live == {("new", 10.0)}, out.entries
    assert k in op.rows and op.rows[k] == ("new",)
    # a later threshold move must still update this row
    out2 = op.step(2, [Delta(), Delta([(tk, (0.0, 10.0, 10.0), -1),
                                       (tk, (0.0, 0.0, 10.0), 1)])])
    assert any(d > 0 and row == ("new", 0.0)
               for _, row, d in out2.entries)
