"""pyfilesystem (fsspec-backed) connector + parquet fs format
(reference: python/pathway/io/pyfilesystem/__init__.py:142; parquet ~
DeltaTableWriter's columnar sink, data_storage.rs:2687)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from tests.utils import rows_of


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


def test_pyfilesystem_read_local(tmp_path):
    (tmp_path / "a.txt").write_bytes(b"alpha")
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "b.bin").write_bytes(b"\x00\x01beta")
    t = pw.io.pyfilesystem.read(f"file://{tmp_path}", mode="static",
                                with_metadata=True)
    got = sorted(rows_of(t), key=lambda r: r[0])
    assert [r[0] for r in got] == [b"\x00\x01beta", b"alpha"]
    metas = [r[1].value for r in got]
    assert metas[0]["path"].endswith("b.bin")
    assert metas[0]["size"] == 6


def test_pyfilesystem_read_memory_fs():
    import fsspec

    fs = fsspec.filesystem("memory")
    fs.pipe("/pwtest/x.txt", b"hello")
    fs.pipe("/pwtest/y.txt", b"world")
    try:
        t = pw.io.pyfilesystem.read(fs, path="/pwtest", mode="static")
        got = sorted(rows_of(t))
        assert got == [(b"hello",), (b"world",)]
    finally:
        fs.rm("/pwtest", recursive=True)


def test_pyfilesystem_streaming_picks_up_new_files(tmp_path):
    import threading
    import time

    (tmp_path / "a.txt").write_bytes(b"one")
    seen = []
    t = pw.io.pyfilesystem.read(f"file://{tmp_path}", mode="streaming",
                                refresh_interval=0.2)
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition:
                    seen.append((row["data"], is_addition)))

    def feed():
        time.sleep(1.0)
        (tmp_path / "b.txt").write_bytes(b"two")

    th = threading.Thread(target=feed, daemon=True)
    th.start()

    runner_th = threading.Thread(
        target=lambda: pw.run(), daemon=True)
    runner_th.start()
    deadline = time.time() + 10
    while time.time() < deadline:
        if {d for d, add in seen if add} == {b"one", b"two"}:
            break
        time.sleep(0.1)
    assert {d for d, add in seen if add} == {b"one", b"two"}


def test_parquet_write_read_roundtrip(tmp_path):
    t = pw.debug.table_from_markdown("""
    name  | qty
    alice | 3
    bob   | 5
    """)
    out = str(tmp_path / "out.parquet")
    pw.io.fs.write(t, out, format="parquet")
    pw.run()

    class S(pw.Schema):
        name: str
        qty: int
        time: int
        diff: int

    G.clear()
    back = pw.io.fs.read(out, format="parquet", schema=S, mode="static")
    got = sorted(rows_of(back))
    assert [(r[0], r[1], r[3]) for r in got] == [
        ("alice", 3, 1), ("bob", 5, 1)]


def test_s3_settings_and_native_client():
    """AwsS3Settings/MinIOSettings plumbing routes into the native SigV4
    client (no s3fs) — full protocol tests live in tests/test_s3.py."""
    s = pw.io.s3.AwsS3Settings(
        bucket_name="b", access_key="ak", secret_access_key="sk",
        endpoint="https://minio.local:9000", region="us-east-1")
    assert s.access_key == "ak" and s.secret_access_key == "sk"
    assert s.endpoint == "https://minio.local:9000"
    m = pw.io.minio.MinIOSettings(
        endpoint="minio.local:9000", bucket_name="b", access_key="ak",
        secret_access_key="sk")
    aws = m.create_aws_settings()
    assert aws.endpoint == "https://minio.local:9000"
    # constructing the streaming source touches no network
    t = pw.io.s3.read("s3://b/prefix", aws_s3_settings=s)
    assert "data" in t.column_names()


def test_elasticsearch_bulk_writer_local_double(tmp_path):
    """pw.io.elasticsearch posts real bulk NDJSON over HTTP — verified
    against an in-test server double (no client lib involved)."""
    import http.server
    import threading

    received = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            received.append((self.path, self.rfile.read(n).decode()))
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b'{"errors": false}')

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        t = pw.debug.table_from_markdown("""
        word | n
        a    | 1
        b    | 2
        """)
        pw.io.elasticsearch.write(
            t, f"http://127.0.0.1:{port}",
            pw.io.elasticsearch.ElasticSearchAuth.apikey("k"),
            index_name="idx")
        pw.run()
    finally:
        srv.shutdown()
    assert received, "no bulk request arrived"
    path, body = received[0]
    assert path == "/_bulk"
    import json

    lines = [json.loads(l) for l in body.strip().splitlines()]
    actions = [l for l in lines if "index" in l]
    docs = [l for l in lines if "word" in l]
    assert all(a["index"]["_index"] == "idx" for a in actions)
    assert sorted(d["word"] for d in docs) == ["a", "b"]
    assert all(d["diff"] == 1 for d in docs)


def test_slack_send_alerts_posts_messages(monkeypatch):
    calls = []

    class _Resp:
        def raise_for_status(self):
            pass

    def fake_post(url, headers=None, json=None, **kw):
        calls.append((url, headers, json))
        return _Resp()

    import requests

    monkeypatch.setattr(requests, "post", fake_post)
    t = pw.debug.table_from_markdown("""
    msg
    alert_one
    alert_two
    """)
    pw.io.slack.send_alerts(t.msg, "C123", "xoxb-token")
    pw.run()
    assert len(calls) == 2
    url, headers, payload = calls[0]
    assert url.endswith("chat.postMessage")
    assert headers["Authorization"] == "Bearer xoxb-token"
    assert {c[2]["text"] for c in calls} == {"alert_one", "alert_two"}
    assert all(c[2]["channel"] == "C123" for c in calls)


def test_redpanda_delegates_to_kafka():
    import pathway_tpu.io.kafka as k
    import pathway_tpu.io.redpanda as rp

    assert rp.read.__module__ == "pathway_tpu.io.redpanda"
    # same plumbing object underneath
    assert rp._kafka is k


def test_http_write_retries_and_logs(caplog, tmp_path):
    """http sink retries with backoff and logs final failures instead of
    silently dropping events (regression: bare except-pass)."""
    import http.server
    import logging
    import threading

    attempts = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            self.rfile.read(n)
            attempts.append(1)
            if len(attempts) < 2:  # first attempt fails, retry succeeds
                self.send_response(503)
            else:
                self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        t = pw.debug.table_from_markdown("msg\nhello")
        pw.io.logstash.write(t, f"http://127.0.0.1:{port}", n_retries=3,
                             retry_delay_s=0.05)
        pw.run()
        assert len(attempts) == 2  # 503 then success
        # unreachable endpoint → logged error, no exception
        G.clear()
        t2 = pw.debug.table_from_markdown("msg\nboom")
        pw.io.http.write(t2, "http://127.0.0.1:9/never", n_retries=1,
                         retry_delay_s=0.01)
        with caplog.at_level(logging.ERROR):
            pw.run()
        assert any("delivery failed after 2" in r.message
                   for r in caplog.records)
    finally:
        srv.shutdown()


def test_gradual_broadcast_insert_before_retract_update():
    """Regression: an update pair arriving insert-first must not drop the
    key from operator state."""
    from pathway_tpu.engine.delta import Delta
    from pathway_tpu.engine.operators import GradualBroadcastOperator
    from pathway_tpu.internals.keys import hash_values

    op = GradualBroadcastOperator()
    k = hash_values("row")
    tk = hash_values("thr")
    op.step(0, [Delta([(k, ("old",), 1)]),
                Delta([(tk, (0.0, 10.0, 10.0), 1)])])
    # update delivered insert-first (exchange merging can permute order)
    out = op.step(1, [Delta([(k, ("new",), 1), (k, ("old",), -1)]),
                      Delta()])
    state = {}
    for key, row, d in out.entries:
        state[row] = state.get(row, 0) + d
    live = {r for r, c in state.items() if c > 0}
    assert live == {("new", 10.0)}, out.entries
    assert k in op.rows and op.rows[k] == ("new",)
    # a later threshold move must still update this row
    out2 = op.step(2, [Delta(), Delta([(tk, (0.0, 10.0, 10.0), -1),
                                       (tk, (0.0, 0.0, 10.0), 1)])])
    assert any(d > 0 and row == ("new", 0.0)
               for _, row, d in out2.entries)


def test_deltalake_write_read_roundtrip(tmp_path):
    """Dependency-free Delta protocol subset: parquet parts + ordered
    _delta_log JSON (reference: DeltaTableReader/Writer via delta-rs)."""
    import json as js

    root = str(tmp_path / "dt")
    t = pw.debug.table_from_markdown("""
    name  | qty | _time | _diff
    alice | 3   | 2     | 1
    bob   | 5   | 2     | 1
    alice | 3   | 4     | -1
    carol | 7   | 4     | 1
    """)
    pw.io.deltalake.write(t, root)
    pw.run()

    # the log is real Delta protocol: version 0 carries protocol+metaData
    log0 = (tmp_path / "dt" / "_delta_log" /
            f"{0:020d}.json").read_text().splitlines()
    actions = [js.loads(l) for l in log0]
    assert any("protocol" in a for a in actions)
    assert any("metaData" in a for a in actions)
    assert any("add" in a for a in actions)

    class S(pw.Schema):
        name: str
        qty: int

    G.clear()
    back = pw.io.deltalake.read(root, schema=S, mode="static")
    got = sorted(rows_of(back))
    # the retraction of alice applied during replay
    assert got == [("bob", 5), ("carol", 7)]


def test_deltalake_streaming_tails_new_versions(tmp_path):
    import threading
    import time

    root = str(tmp_path / "dt")
    # seed version 0 through the writer
    t = pw.debug.table_from_markdown("name\nseed")
    pw.io.deltalake.write(t, root)
    pw.run()
    G.clear()

    class S(pw.Schema):
        name: str

    seen = []
    live = pw.io.deltalake.read(root, schema=S, mode="streaming")
    pw.io.subscribe(live, on_change=lambda key, row, time, is_addition:
                    seen.append(row["name"]))

    def feed():
        time.sleep(1.2)
        G2 = []
        # write a NEW version with a fresh pipeline (append-only tail)
        import pathway_tpu as pw2
        from pathway_tpu.internals.parse_graph import G as PG

        # separate graph context: build + run a second writer run
        snapshot = list(PG.output_binders)
        t2 = pw2.debug.table_from_markdown("name\nlive_row")
        pw2.io.deltalake.write(t2, root)
        new_binders = [b for b in PG.output_binders
                       if b not in snapshot]
        from pathway_tpu.internals.runner import GraphRunner

        r = GraphRunner()
        for b in new_binders:
            b(r)
        r.run_batch()

    threading.Thread(target=feed, daemon=True).start()
    threading.Thread(target=lambda: pw.run(), daemon=True).start()
    deadline = time.time() + 10
    while time.time() < deadline and set(seen) != {"seed", "live_row"}:
        time.sleep(0.1)
    assert set(seen) == {"seed", "live_row"}


def test_deltalake_remove_actions_and_duplicates(tmp_path):
    """delta-rs interop semantics: 'remove' actions retract a part's rows;
    duplicate keyless rows stay distinct occurrences."""
    import json as js

    import pyarrow as pa
    import pyarrow.parquet as pq

    root = tmp_path / "dt"
    (root / "_delta_log").mkdir(parents=True)

    def commit(version, actions):
        p = root / "_delta_log" / f"{version:020d}.json"
        p.write_text("\n".join(js.dumps(a) for a in actions) + "\n")

    def part(name, rows):
        pq.write_table(pa.Table.from_pylist(rows), str(root / name))

    # v0: two identical keyless rows + one other
    part("p0.parquet", [{"name": "dup", "qty": 1, "time": 0, "diff": 1},
                        {"name": "dup", "qty": 1, "time": 0, "diff": 1},
                        {"name": "solo", "qty": 2, "time": 0, "diff": 1}])
    commit(0, [{"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
               {"add": {"path": "p0.parquet", "size": 1,
                        "partitionValues": {}, "dataChange": True}}])
    # v1: a compaction-style rewrite — remove p0, re-add survivors only
    part("p1.parquet", [{"name": "dup", "qty": 1, "time": 1, "diff": 1}])
    commit(1, [{"remove": {"path": "p0.parquet", "dataChange": True}},
               {"add": {"path": "p1.parquet", "size": 1,
                        "partitionValues": {}, "dataChange": True}}])

    class S(pw.Schema):
        name: str
        qty: int

    t = pw.io.deltalake.read(str(root), schema=S, mode="static")
    got = sorted(rows_of(t))
    # after the rewrite exactly ONE dup row survives, solo is gone
    assert got == [("dup", 1)]

    # duplicates before any remove: both occurrences visible
    G.clear()
    (root / "_delta_log" / f"{1:020d}.json").unlink()
    t2 = pw.io.deltalake.read(str(root), schema=S, mode="static")
    got2 = sorted(rows_of(t2))
    assert got2 == [("dup", 1), ("dup", 1), ("solo", 2)]


def test_streaming_join_against_static_dimension(tmp_path):
    """Regression: streaming mode must feed static tables at startup — a
    live stream joined with a static dimension table produced zero rows
    (the batch path fed them, the streaming loop never did)."""
    import threading
    import time

    d = tmp_path / "orders"
    d.mkdir()
    (d / "a.jsonl").write_text('{"item": "widget", "qty": 2}\n')

    class Order(pw.Schema):
        item: str
        qty: int

    class Cat(pw.Schema):
        item: str
        cat: str

    orders = pw.io.fs.read(str(d), format="json", schema=Order,
                           mode="streaming")
    cats = pw.debug.table_from_rows(Cat, [("widget", "tools"),
                                          ("gizmo", "toys")])
    joined = orders.join(cats, orders.item == cats.item).select(
        orders.item, orders.qty, cats.cat)
    seen = []
    pw.io.subscribe(joined, on_change=lambda key, row, time, is_addition:
                    seen.append((row["item"], row["cat"], is_addition)))

    def feed():
        time.sleep(1.5)
        (d / "b.jsonl").write_text('{"item": "gizmo", "qty": 1}\n')

    threading.Thread(target=feed, daemon=True).start()
    threading.Thread(target=lambda: pw.run(), daemon=True).start()
    deadline = time.time() + 10
    while time.time() < deadline and len(seen) < 2:
        time.sleep(0.1)
    assert ("widget", "tools", True) in seen
    assert ("gizmo", "toys", True) in seen


def test_reference_convenience_wrappers():
    """Thin reference-surface wrappers: kafka simple_read/upstash settings,
    s3 DigitalOcean/Wasabi endpoints, postgres write_snapshot alias,
    gdrive metadata enrichment."""
    import pathway_tpu as pw

    # kafka: settings construction (no broker needed — inspect the source)
    t = pw.io.kafka.simple_read("srv:9092", "top", read_only_new=True)
    src = t._plan.params["datasource"]
    assert src.settings["bootstrap.servers"] == "srv:9092"
    assert src.settings["auto.offset.reset"] == "latest"
    t2 = pw.io.kafka.read_from_upstash("up:9092", "user", "pw", "top")
    s2 = t2._plan.params["datasource"].settings
    assert s2["security.protocol"] == "sasl_ssl"
    assert s2["sasl.mechanism"] == "SCRAM-SHA-256"

    @pw.io.kafka.check_raw_and_plaintext_only_kwargs
    def fake_write(table, **kwargs):
        return "ok"

    import pytest as _pytest

    with _pytest.raises(ValueError, match="key"):
        fake_write(None, format="json", key="k")
    assert fake_write(None, format="raw", key="k") == "ok"

    # s3 settings map to the provider endpoints
    do = pw.io.s3.DigitalOceanS3Settings(
        bucket_name="b", access_key="a", secret_access_key="s",
        region="ams3")
    assert do._as_aws().endpoint == "https://ams3.digitaloceanspaces.com"
    wa = pw.io.s3.WasabiS3Settings(
        bucket_name="b", access_key="a", secret_access_key="s",
        region="us-west-1")
    assert wa._as_aws().endpoint == "https://s3.us-west-1.wasabisys.com"

    # gdrive metadata enrichment
    meta = pw.io.gdrive.extend_metadata({"id": "f1", "name": "doc.txt"})
    assert meta["url"].endswith("/f1/")
    assert meta["path"] == "doc.txt"
    assert meta["status"] == pw.io.gdrive.STATUS_DOWNLOADED
    assert isinstance(meta["seen_at"], int)

    # postgres write_snapshot delegates to write(output_table_type=snapshot)
    try:
        import psycopg2  # noqa: F401
    except ImportError:
        with _pytest.raises(ImportError, match="psycopg2"):
            pw.io.postgres.write_snapshot(
                pw.debug.table_from_markdown("a\n1"), {}, "t", ["a"])
