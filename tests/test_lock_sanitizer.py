"""Runtime lock-order sanitizer (engine/locking.py): factory behavior with
the sanitizer off and on, inversion detection (raise + report modes),
held-across-blocking reporting, condition-wait bookkeeping, and the
excepthook/thread-factory wiring (engine/threads.py)."""

from __future__ import annotations

import threading

import pytest

from pathway_tpu.engine import locking
from pathway_tpu.engine.locking import (HeldAcrossBlockingViolation,
                                        LockOrderViolation, blocking_call,
                                        create_condition, create_lock,
                                        create_rlock, held_locks,
                                        violations)


@pytest.fixture
def sanitizer(monkeypatch):
    monkeypatch.setenv("PATHWAY_LOCK_SANITIZER", "1")
    locking._reset_for_tests()
    yield
    locking._reset_for_tests()


@pytest.fixture
def sanitizer_report(monkeypatch):
    monkeypatch.setenv("PATHWAY_LOCK_SANITIZER", "report")
    locking._reset_for_tests()
    yield
    locking._reset_for_tests()


# ---------------------------------------------------------------------------
# factories
# ---------------------------------------------------------------------------

def test_factories_return_plain_primitives_when_off(monkeypatch):
    monkeypatch.delenv("PATHWAY_LOCK_SANITIZER", raising=False)
    assert isinstance(create_lock("X.a"), type(threading.Lock()))
    assert isinstance(create_rlock("X.b"), type(threading.RLock()))
    assert isinstance(create_condition("X.c"), threading.Condition)


def test_sanitized_lock_basics(sanitizer):
    lock = create_lock("T.basics")
    assert not lock.locked()
    with lock:
        assert lock.locked()
        assert held_locks() == ["T.basics"]
    assert not lock.locked()
    assert held_locks() == []


def test_sanitized_rlock_is_reentrant(sanitizer):
    lock = create_rlock("T.rlock")
    with lock:
        with lock:
            assert held_locks() == ["T.rlock", "T.rlock"]
    assert held_locks() == []
    assert violations() == []


# ---------------------------------------------------------------------------
# lock-order inversion
# ---------------------------------------------------------------------------

def test_inversion_raises_and_names_both_locks(sanitizer):
    a = create_lock("T.a")
    b = create_lock("T.b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderViolation, match="T.a.*T.b|T.b.*T.a"):
            a.acquire()
    # the violation is recorded AND the physical lock was put back —
    # a raise must not wedge every other thread on the lock forever
    assert [v["kind"] for v in violations()] == ["lock-order"]
    assert a.acquire(blocking=False)
    a.release()


def test_inversion_detected_across_threads(sanitizer):
    # thread 1 establishes a→b; the MAIN thread then takes b→a: the graph
    # is global, so the cycle is caught even though no single thread ever
    # held both orders
    a = create_lock("T.x")
    b = create_lock("T.y")

    def establish():
        with a:
            with b:
                pass

    t = threading.Thread(target=establish)
    t.start()
    t.join()
    with b:
        with pytest.raises(LockOrderViolation):
            with a:
                pass


def test_consistent_order_never_fires(sanitizer):
    a = create_lock("T.c1")
    b = create_lock("T.c2")
    for _ in range(3):
        with a:
            with b:
                pass
    assert violations() == []


def test_report_mode_records_without_raising(sanitizer_report):
    a = create_lock("T.r1")
    b = create_lock("T.r2")
    with a:
        with b:
            pass
    with b:
        with a:  # inversion: logged, not raised
            pass
    assert [v["kind"] for v in violations()] == ["lock-order"]


def test_same_name_locks_share_identity(sanitizer):
    # two instances of one class share the lock name on purpose: no
    # self-edge, no false inversion from instance pairs
    a1 = create_lock("Inst._lock")
    a2 = create_lock("Inst._lock")
    with a1:
        with a2:
            pass
    with a2:
        with a1:
            pass
    assert violations() == []


# ---------------------------------------------------------------------------
# held-across-blocking
# ---------------------------------------------------------------------------

def test_blocking_call_with_lock_held_raises(sanitizer):
    lock = create_lock("T.held")
    with lock:
        with pytest.raises(HeldAcrossBlockingViolation, match="fsync"):
            with blocking_call("persistence.fsync"):
                pass
    assert [v["kind"] for v in violations()] == ["held-across-blocking"]


def test_blocking_call_without_lock_is_free(sanitizer):
    with blocking_call("persistence.fsync"):
        pass
    assert violations() == []


def test_blocking_call_noop_when_sanitizer_off(monkeypatch):
    monkeypatch.delenv("PATHWAY_LOCK_SANITIZER", raising=False)
    with blocking_call("anything"):
        pass


# ---------------------------------------------------------------------------
# sanitized conditions
# ---------------------------------------------------------------------------

def test_condition_wait_releases_only_its_own_lock(sanitizer):
    cv = create_condition("T.cv")
    # waiting while holding ONLY the condition is the normal protocol
    with cv:
        cv.wait(timeout=0.01)
    assert violations() == []
    assert held_locks() == []


def test_condition_wait_with_second_lock_is_a_violation(sanitizer):
    lock = create_lock("T.other")
    cv = create_condition("T.cv2")
    with lock:
        with cv:
            with pytest.raises(HeldAcrossBlockingViolation,
                               match="T.other"):
                cv.wait(timeout=0.01)
    assert held_locks() == []


def test_condition_notify_roundtrip(sanitizer):
    cv = create_condition("T.cv3")
    state = {"ready": False}
    got = []

    def consumer():
        with cv:
            while not state["ready"]:
                cv.wait(timeout=5.0)
            got.append(True)

    t = threading.Thread(target=consumer)
    t.start()
    with cv:
        state["ready"] = True
        cv.notify_all()
    t.join(timeout=5.0)
    assert got == [True]
    assert violations() == []


# ---------------------------------------------------------------------------
# engine integration: the real lock points run sanitized
# ---------------------------------------------------------------------------

def test_device_bridge_runs_sanitized(sanitizer):
    from pathway_tpu.engine.device_bridge import DeviceBridge

    bridge = DeviceBridge(max_inflight=2, name="sanitized-bridge")
    seen = []
    for t in range(1, 6):
        bridge.submit(t, lambda t=t: seen.append(t))
    bridge.barrier()
    bridge.close()
    assert seen == [1, 2, 3, 4, 5]
    assert bridge.resolved_watermark() == 5
    assert violations() == []


# ---------------------------------------------------------------------------
# thread factory + excepthook (engine/threads.py)
# ---------------------------------------------------------------------------

def test_spawn_names_and_inventories_threads():
    import time

    from pathway_tpu.engine import threads

    release = threading.Event()
    t = threads.spawn(release.wait, name="unit-test-worker")
    try:
        assert t.name == "pathway-tpu-unit-test-worker"
        assert t.daemon
        deadline = time.monotonic() + 2.0
        names = []
        while time.monotonic() < deadline:
            names = [e["name"] for e in threads.live_threads()]
            if "pathway-tpu-unit-test-worker" in names:
                break
        assert "pathway-tpu-unit-test-worker" in names
    finally:
        release.set()
        t.join(timeout=5.0)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_uncaught_thread_exception_lands_in_errorlog_and_healthz():
    # the chained previous hook still fires (pytest's own warning proves
    # the chain is intact); the assertion is about OUR side effects
    from pathway_tpu.engine import threads
    from pathway_tpu.engine.supervisor import ConnectorSupervisor
    from pathway_tpu.internals import error as error_mod

    threads._reset_crashes_for_tests()
    before = len(error_mod._global_log.entries)
    # the supervisor exists BEFORE its thread dies (the run's ordering);
    # crash accounting is epoch-scoped to the supervisor's creation
    sup = ConnectorSupervisor()

    def boom():
        raise RuntimeError("seeded thread crash")

    t = threads.spawn(boom, name="crasher")
    t.join(timeout=5.0)
    crashes = threads.crashed_threads()
    try:
        assert any("seeded thread crash" in c["error"] for c in crashes)
        new = error_mod._global_log.entries[before:]
        assert any(e["kind"] == "thread"
                   and "seeded thread crash" in e["message"] for e in new)
        # the supervisor's health predicate (hence /healthz) degrades
        assert not sup.healthy()
        # ...but a NEW run in the same process starts healthy: old
        # crashes must not poison it forever
        assert ConnectorSupervisor().healthy()
    finally:
        threads._reset_crashes_for_tests()
