"""Property-style differential testing: randomly generated pipelines over
randomly generated update streams must produce byte-identical consolidated
streams at n_workers 1 and 8 (SURVEY §5: determinism IS the correctness
mechanism — same input prefix ⇒ same output at each timestamp)."""

from __future__ import annotations

import random

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_rows
from pathway_tpu.engine.delta import row_fingerprint
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


def _random_stream(rng: random.Random, n_rows: int):
    """Update stream with mid-stream retractions of previously live rows."""
    rows = []
    live = []
    for i in range(n_rows):
        t = 2 * (1 + i // 7)
        if live and rng.random() < 0.25:
            victim = live.pop(rng.randrange(len(live)))
            rows.append(victim[:3] + (t, -1))
        else:
            row = (f"k{rng.randrange(9)}", rng.randrange(20),
                   f"s{rng.randrange(5)}")
            rows.append(row + (t, 1))
            live.append(row)
    return rows


def _build(rng: random.Random):
    class S(pw.Schema):
        k: str
        x: int
        tag: str

    class D(pw.Schema):
        tag: str
        w: int

    t = table_from_rows(S, _random_stream(rng, 80), is_stream=True)
    dim = table_from_rows(D, [(f"s{i}", 10 * i) for i in range(5)])
    outs = []
    # random op chain
    if rng.random() < 0.5:
        t = t.filter(t.x >= rng.randrange(6))
    t = t.select(t.k, t.tag, y=t.x * 2 + 1)
    outs.append(t)
    g = t.groupby(t.k).reduce(
        t.k,
        n=pw.reducers.count(),
        s=pw.reducers.sum(t.y),
        mn=pw.reducers.min(t.y),
        mx=pw.reducers.max(t.y),
    )
    outs.append(g)
    if rng.random() < 0.4:
        win = pw.temporal.windowby(
            t, t.y, window=pw.temporal.tumbling(rng.choice([3, 5, 8])),
            instance=t.tag,
        ).reduce(
            tag=pw.this._pw_instance,
            start=pw.this._pw_window_start,
            s=pw.reducers.sum(pw.this.y),
        )
        outs.append(win)
    mode = rng.choice(["inner", "left", "outer"])
    joined = {
        "inner": t.join, "left": t.join_left, "outer": t.join_outer,
    }[mode](dim, t.tag == dim.tag).select(t.k, t.y, dim.w)
    outs.append(joined)
    g2 = joined.groupby(joined.k).reduce(
        joined.k, tot=pw.reducers.sum(pw.coalesce(joined.w, 0)))
    outs.append(g2)
    return outs


def _run(seed: int, n_workers: int):
    G.clear()
    rng = random.Random(seed)
    outs = _build(rng)
    runner = GraphRunner()
    caps = [runner.capture(o) for o in outs]
    runner.run_batch(n_workers=n_workers)
    result = [
        sorted((int(k), row_fingerprint(r), t, d)
               for k, r, t, d in c.consolidated_events())
        for c in caps
    ]
    G.clear()
    return result


@pytest.mark.parametrize("seed", range(12))
def test_random_pipeline_identical_across_worker_counts(seed):
    assert _run(seed, 1) == _run(seed, 8)
