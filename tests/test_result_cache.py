"""Semantic result cache (engine/result_cache.py): keying, incremental
delta invalidation, and the operator/router integration contracts.

Pins the subsystem's load-bearing guarantees:

- **byte-identity** — a cache-enabled run emits *exactly* the deltas a
  cache-disabled run emits, across seeded index churn (insert, delete,
  slab growth) interleaved with a Zipf-repeated query stream;
- **no stale serve** — a delta landing in a cached entry's touched page
  set that can beat its k-th score invalidates the entry before the
  next serve (the staleness window is zero ticks, not a TTL);
- **incremental survival** — deltas that provably cannot change an
  answer (outside the beat margin, or uncovered by the entry's page
  set only when they cannot enter it) leave the entry hot;
- **router watermark fencing** — the fleet-level response cache serves
  only under an unchanged (replica, index_version) watermark and drops
  entries the moment the watermark moves.
"""

from __future__ import annotations

import numpy as np
import pytest

from pathway_tpu.engine.delta import Delta
from pathway_tpu.engine.index_ops import ExternalIndexOperator
from pathway_tpu.engine.result_cache import (ResultCache, RouterResultCache,
                                             fingerprint, live_cache_stats,
                                             maybe_result_cache,
                                             result_cache_enabled)
from pathway_tpu.ops.knn import BruteForceKnnIndex


def _operator(idx, **kw):
    return ExternalIndexOperator(idx, data_vec_pos=0, data_filter_pos=None,
                                 query_vec_pos=0, query_limit_pos=1,
                                 query_filter_pos=None, **kw)


def _step(op, t, data=(), queries=()):
    return op.step(t, [Delta(list(data)), Delta(list(queries))])


# ---------------------------------------------------------------------------
# unit: keying + invalidation rules
# ---------------------------------------------------------------------------

def test_fingerprint_covers_vector_and_limit():
    v = np.arange(4, dtype=np.float32)
    assert fingerprint(v, 3) == fingerprint(v.copy(), 3)
    assert fingerprint(v, 3) != fingerprint(v, 4)
    w = v.copy()
    w[0] += 1e-6
    assert fingerprint(v, 3) != fingerprint(w, 3)


def test_env_knob_disables_cache(monkeypatch):
    monkeypatch.setenv("PATHWAY_RESULT_CACHE", "0")
    assert result_cache_enabled() is False
    idx = BruteForceKnnIndex(4, reserved_space=16)
    assert idx.result_cache is None
    monkeypatch.setenv("PATHWAY_RESULT_CACHE", "1")
    assert maybe_result_cache(BruteForceKnnIndex(4, reserved_space=16)) \
        is not None


def test_far_insert_survives_near_insert_dooms():
    cache = ResultCache(page_rows=8, metric="l2sq")
    q = np.zeros(4, np.float32)
    reply = ((b"a", 1.0), (b"b", 2.0))
    cache.fill(fingerprint(q, 2), reply, frozenset({0, 1}), 2.0, q)
    # covered page, but distance 100^2*4 >> kth: entry survives
    cache.on_insert_batch(np.array([3]), [b"z"],
                          np.full((1, 4), 100.0, np.float32))
    assert cache.lookup(fingerprint(q, 2)) == reply
    # covered page and inside the k-th radius: entry is doomed
    cache.on_insert_batch(np.array([4]), [b"y"],
                          np.zeros((1, 4), np.float32))
    assert cache.lookup(fingerprint(q, 2)) is None
    assert cache.invalidations == 1


def test_uncovered_page_insert_always_invalidates():
    cache = ResultCache(page_rows=8, metric="l2sq")
    q = np.zeros(4, np.float32)
    cache.fill(fingerprint(q, 1), ((b"a", 1.0),), frozenset({0}), 1.0, q)
    # slot 80 -> page 10, outside the entry's coverage: the scan that
    # filled the entry never saw that page, so distance is no defence
    cache.on_insert_batch(np.array([80]), [b"far"],
                          np.full((1, 4), 50.0, np.float32))
    assert cache.lookup(fingerprint(q, 1)) is None


def test_short_reply_always_beatable():
    # reply shorter than the limit (kth=None): any covered insert wins
    cache = ResultCache(page_rows=8, metric="l2sq")
    q = np.zeros(4, np.float32)
    cache.fill(fingerprint(q, 5), ((b"a", 1.0),), frozenset({0}), None, q)
    cache.on_insert_batch(np.array([1]), [b"b"],
                          np.full((1, 4), 99.0, np.float32))
    assert cache.lookup(fingerprint(q, 5)) is None


def test_reinsert_of_reply_key_invalidates():
    cache = ResultCache(page_rows=8, metric="l2sq")
    q = np.zeros(4, np.float32)
    cache.fill(fingerprint(q, 1), ((b"a", 1.0),), frozenset({0}), 1.0, q)
    # upsert of a key already present in the reply must doom the entry
    # even when the new vector is far away (the old row is replaced)
    cache.on_insert_batch(np.array([2]), [b"a"],
                          np.full((1, 4), 70.0, np.float32))
    assert cache.lookup(fingerprint(q, 1)) is None


def test_delete_invalidates_by_page_membership():
    cache = ResultCache(page_rows=8, metric="l2sq")
    q = np.zeros(4, np.float32)
    cache.fill(fingerprint(q, 1), ((b"a", 1.0),), frozenset({0, 1}), 1.0, q)
    cache.on_delete(80, b"other")          # page 10: uncovered, survives
    assert cache.lookup(fingerprint(q, 1)) is not None
    cache.on_delete(9, b"other")           # page 1: covered, doomed
    assert cache.lookup(fingerprint(q, 1)) is None


def test_lru_eviction_bounds_entries():
    cache = ResultCache(page_rows=8, metric="l2sq", max_entries=4)
    for i in range(10):
        q = np.full(4, float(i), np.float32)
        cache.fill(fingerprint(q, 1), ((b"k", 0.0),), frozenset({0}), 0.0, q)
    assert cache.stats()["entries"] == 4
    assert cache.evictions == 6


def test_cosine_metric_beat_test():
    cache = ResultCache(page_rows=8, metric="cos")
    q = np.array([1.0, 0.0, 0.0, 0.0], np.float32)
    # kth cosine distance 0.5: orthogonal insert (dist 1.0) survives,
    # parallel insert (dist 0.0) dooms
    cache.fill(fingerprint(q, 2), ((b"a", 0.1), (b"b", 0.5)),
               frozenset({0}), 0.5, q)
    cache.on_insert_batch(np.array([1]), [b"c"],
                          np.array([[0.0, 1.0, 0.0, 0.0]], np.float32))
    assert cache.lookup(fingerprint(q, 2)) is not None
    cache.on_insert_batch(np.array([2]), [b"d"],
                          np.array([[2.0, 0.0, 0.0, 0.0]], np.float32))
    assert cache.lookup(fingerprint(q, 2)) is None


# ---------------------------------------------------------------------------
# operator integration: staleness + byte-identity under churn
# ---------------------------------------------------------------------------

def test_covering_delta_invalidates_before_next_serve():
    """The ISSUE's staleness pin: a delta landing in a touched page that
    beats the k-th score must be visible to the very next serve."""
    idx = BruteForceKnnIndex(4, reserved_space=64)
    op = _operator(idx)
    rng = np.random.default_rng(7)
    base = rng.normal(size=(20, 4)).astype(np.float32) + 10.0
    _step(op, 0, data=[(i, (base[i],), 1) for i in range(20)])
    q = np.zeros(4, np.float32)
    out1 = _step(op, 1, queries=[(100, (q, 2), 1)])
    out2 = _step(op, 2, queries=[(101, (q, 2), 1)])
    st = idx.result_cache.stats()
    assert st["hits"] == 1 and st["entries"] == 1
    # ingest an exact match for q: beats kth, lands in a touched page
    _step(op, 3, data=[(999, (q.copy(),), 1)])
    assert idx.result_cache.stats()["entries"] == 0
    out3 = _step(op, 4, queries=[(102, (q, 2), 1)])
    reply = list(out3.entries)[0][1][0]
    assert reply[0][0] == 999                  # fresh row is served
    assert list(out1.entries)[0][1] == list(out2.entries)[0][1]
    assert list(out3.entries)[0][1] != list(out1.entries)[0][1]


def test_delete_of_served_row_invalidates_before_next_serve():
    idx = BruteForceKnnIndex(4, reserved_space=64)
    op = _operator(idx)
    vecs = np.eye(4, dtype=np.float32)
    _step(op, 0, data=[(i, (vecs[i],), 1) for i in range(4)])
    q = np.array([1.0, 0.0, 0.0, 0.0], np.float32)
    out1 = _step(op, 1, queries=[(100, (q, 1), 1)])
    assert list(out1.entries)[0][1][0][0][0] == 0
    _step(op, 2, data=[(0, (vecs[0],), -1)])   # retract the best row
    out2 = _step(op, 3, queries=[(101, (q, 1), 1)])
    assert list(out2.entries)[0][1][0][0][0] != 0


def test_duplicate_queries_in_one_tick_share_one_miss():
    idx = BruteForceKnnIndex(4, reserved_space=64)
    op = _operator(idx)
    _step(op, 0, data=[(i, (np.full(4, float(i), np.float32),), 1)
                       for i in range(8)])
    q = np.ones(4, np.float32)
    out = _step(op, 1, queries=[(100, (q, 2), 1), (101, (q, 2), 1),
                                (102, (q, 2), 1)])
    rows = {k: row for k, row, _d in out.entries}
    assert rows[100] == rows[101] == rows[102]
    assert idx.result_cache.fills == 1         # one search, two reuses


def _churn_run(seed, cache_on, monkeypatch):
    monkeypatch.setenv("PATHWAY_RESULT_CACHE", "1" if cache_on else "0")
    idx = BruteForceKnnIndex(6, reserved_space=32)   # small: forces growth
    assert (idx.result_cache is not None) is cache_on
    op = _operator(idx)
    rng = np.random.default_rng(seed)
    qpool = rng.normal(size=(24, 6)).astype(np.float32)
    live, next_key, next_q = [], 0, 10_000
    outputs = []
    for t in range(40):
        data = []
        n_ins = int(rng.integers(0, 7))      # growth past 32 reserved rows
        for _ in range(n_ins):
            vec = rng.normal(size=6).astype(np.float32)
            data.append((next_key, (vec,), 1))
            live.append((next_key, vec))
            next_key += 1
        if live and rng.random() < 0.35:
            j = int(rng.integers(0, len(live)))
            key, vec = live.pop(j)
            data.append((key, (vec,), -1))
        queries = []
        for _ in range(int(rng.integers(0, 4))):
            qi = min(int(rng.zipf(1.3)) - 1, len(qpool) - 1)  # hot head
            queries.append((next_q, (qpool[qi], 3), 1))
            next_q += 1
        outputs.append(sorted(_step(op, t, data=data, queries=queries)
                              .entries))
    if cache_on:
        st = idx.result_cache.stats()
        assert st["hits"] > 0                # the Zipf head actually hit
        assert st["invalidations"] > 0       # churn actually invalidated
    return outputs


def test_property_cache_on_byte_identical_to_cache_off(monkeypatch):
    """The acceptance pin: across seeded insert/delete/grow churn with a
    Zipf query stream, the cache changes *when* work happens, never
    *what* is emitted."""
    for seed in (3, 11, 42):
        on = _churn_run(seed, True, monkeypatch)
        off = _churn_run(seed, False, monkeypatch)
        assert on == off


def test_data_tick_bumps_version_once(monkeypatch):
    monkeypatch.setenv("PATHWAY_RESULT_CACHE", "1")
    idx = BruteForceKnnIndex(4, reserved_space=16)
    op = _operator(idx)
    v0 = idx.result_cache.version
    _step(op, 0, data=[(0, (np.zeros(4, np.float32),), 1)])
    assert idx.result_cache.version == v0 + 1
    _step(op, 1, queries=[(100, (np.zeros(4, np.float32), 1), 1)])
    assert idx.result_cache.version == v0 + 1      # queries do not bump
    st = live_cache_stats()
    assert st is not None and st["version"] >= v0 + 1


def test_cache_hits_feed_qos_coalescing_counter():
    from pathway_tpu.engine.qos import (QosConfig, QosController,
                                        install_controller)

    class _Tracker:
        slo_ms = 20.0

        def burn_rate(self):
            return 0.0

        def window_size(self):
            return 0

        def quantiles_ms(self):
            return None

    ctl = QosController(QosConfig(), _Tracker())
    install_controller(ctl)
    try:
        idx = BruteForceKnnIndex(4, reserved_space=16)
        op = _operator(idx)
        _step(op, 0, data=[(i, (np.full(4, float(i), np.float32),), 1)
                           for i in range(4)])
        q = np.ones(4, np.float32)
        _step(op, 1, queries=[(100, (q, 2), 1)])
        _step(op, 2, queries=[(101, (q, 2), 1)])
        assert ctl.coalesced_answers == 1
        assert ctl.summary()["coalesced_answers"] == 1
        assert ctl.heartbeat_state()["coalesced_answers"] == 1
    finally:
        install_controller(None)


def test_filtered_queries_bypass_the_cache():
    idx = BruteForceKnnIndex(4, reserved_space=16)
    op = ExternalIndexOperator(idx, data_vec_pos=0, data_filter_pos=1,
                               query_vec_pos=0, query_limit_pos=1,
                               query_filter_pos=2)
    _step(op, 0, data=[(i, (np.full(4, float(i), np.float32), "x"), 1)
                       for i in range(4)])
    q = np.zeros(4, np.float32)
    _step(op, 1, queries=[(100, (q, 2, "x == `x`"), 1)])
    _step(op, 2, queries=[(101, (q, 2, "x == `x`"), 1)])
    st = idx.result_cache.stats()
    assert st["hits"] == 0 and st["entries"] == 0


# ---------------------------------------------------------------------------
# router fleet cache: watermark fencing
# ---------------------------------------------------------------------------

def test_router_cache_serves_only_under_held_watermark():
    rc = RouterResultCache()
    key = RouterResultCache.key("POST", "/query", b'{"q": 1}')
    wm1 = frozenset({("r0", 3), ("r1", 3)})
    assert rc.lookup(key, wm1) is None
    rc.fill(key, wm1, 200, b"answer", "application/json")
    assert rc.lookup(key, wm1) == (200, b"answer", "application/json")
    # watermark moved (one replica advanced): entry is dropped, miss
    wm2 = frozenset({("r0", 4), ("r1", 3)})
    assert rc.lookup(key, wm2) is None
    assert rc.invalidations == 1
    assert rc.lookup(key, wm2) is None          # really gone
    # unknown watermark (replica without index_version): no serve, no fill
    rc.fill(key, None, 200, b"answer", "application/json")
    assert rc.lookup(key, None) is None
    assert rc.stats()["entries"] == 0


def test_router_cache_key_separates_method_path_body():
    k = RouterResultCache.key
    assert k("POST", "/query", b"a") == k("POST", "/query", b"a")
    assert k("POST", "/query", b"a") != k("GET", "/query", b"a")
    assert k("POST", "/query", b"a") != k("POST", "/query2", b"a")
    assert k("POST", "/query", b"a") != k("POST", "/query", b"b")


def test_router_cache_lru_eviction(monkeypatch):
    monkeypatch.setenv("PATHWAY_ROUTER_CACHE_ENTRIES", "3")
    rc = RouterResultCache()
    wm = frozenset({("r0", 1)})
    for i in range(6):
        rc.fill(RouterResultCache.key("POST", "/query", b"%d" % i),
                wm, 200, b"x", "application/json")
    assert rc.stats()["entries"] == 3


def test_router_cache_path_and_watermark_plumbing():
    from pathway_tpu.engine.router import QueryRouter, ReplicaEndpoint

    router = QueryRouter(write_paths=("/ingest",), cache_routes=("/query",))
    assert router.response_cache is not None
    assert router.is_cache_path("/query")
    assert router.is_cache_path("/query/v2")
    assert not router.is_cache_path("/ingest")
    assert router._fleet_watermark() is None       # no replicas alive
    ep = ReplicaEndpoint("r0", "replica", "127.0.0.1", 1, None)
    ep.index_version = 5
    router._endpoints["r0"] = ep
    assert router._fleet_watermark() == frozenset({("r0", 5)})
    ep.index_version = None                        # version unknown: fenced
    assert router._fleet_watermark() is None
