"""Checkpoint/resume (SURVEY §5 checkpoint; reference: src/persistence/ +
integration_tests/wordcount kill-and-recover harness, test_recovery.py:25)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from tests.utils import wait_result_with_checker

import pathway_tpu as pw
from pathway_tpu.engine.persistence import SnapshotLog
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


# ---------------------------------------------------------------------------
# snapshot log
# ---------------------------------------------------------------------------

def test_snapshot_log_roundtrip(tmp_path):
    log = SnapshotLog(str(tmp_path / "s.snap"))
    log.append(1, [("k1", ("a",), 1, None)])
    log.append(2, [("k2", ("b",), 1, ("row", "f", 0.0, 0, True))])
    log.close()
    records = SnapshotLog(str(tmp_path / "s.snap")).read_all()
    assert len(records) == 2
    assert records[0] == (1, [("k1", ("a",), 1, None)])
    assert records[1][1][0][3] == ("row", "f", 0.0, 0, True)


def test_snapshot_log_truncated_tail(tmp_path):
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    log.close()
    with open(path, "ab") as f:
        f.write(b"\x40\x00\x00\x00\x00\x00\x00\x00partial")  # crash mid-append
    records = SnapshotLog(path).read_all()
    assert len(records) == 1  # the torn record is dropped


def test_snapshot_log_append_after_torn_tail(tmp_path):
    """Appends after a torn record must stay readable (the torn bytes are
    truncated first), or the log stops making durable progress forever."""
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    log.close()
    with open(path, "ab") as f:
        f.write(b"\x40\x00\x00\x00\x00\x00\x00\x00partial")
    log2 = SnapshotLog(path)
    log2.append(2, [("k2", ("b",), 1, None)])
    log2.close()
    records = SnapshotLog(path).read_all()
    assert [t for t, _ in records] == [1, 2]


def test_duplicate_persistent_id_rejected(tmp_path):
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.io._datasource import CallbackSource, Session

    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(tmp_path / "p")))
    driver = PersistenceDriver(cfg)
    schema = pw.schema_from_types(x=int)
    s1 = CallbackSource(lambda: iter(()), schema)
    s1.persistent_id = "dup"
    s2 = CallbackSource(lambda: iter(()), schema)
    s2.persistent_id = "dup"
    driver.attach_source(s1, Session())
    with pytest.raises(ValueError, match="unique persistent_id"):
        driver.attach_source(s2, Session())


# ---------------------------------------------------------------------------
# in-process resume: python source (skip-N protocol)
# ---------------------------------------------------------------------------

def _run_counts(words: list[str], backend) -> dict[str, int]:
    """Stream `words`, persist via `backend`, return final word counts."""
    G.clear()

    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            for w in words:
                self.next(word=w)

    t = pw.io.python.read(
        Subject(), schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=10, persistent_id="words")
    counts = t.groupby(t.word).reduce(word=t.word, c=pw.reducers.count())
    state: dict[str, int] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["word"]] = row["c"]
        elif state.get(row["word"]) == row["c"]:
            del state[row["word"]]

    pw.io.subscribe(counts, on_change)
    pw.run(persistence_config=pw.persistence.Config.simple_config(backend))
    return state


def test_python_source_resume_mock_backend():
    backend = pw.persistence.Backend.mock()
    first = _run_counts(["a", "b", "a"], backend)
    assert first == {"a": 2, "b": 1}
    # restart: the source deterministically re-emits its prefix, plus new rows
    second = _run_counts(["a", "b", "a", "c", "b"], backend)
    assert second == {"a": 2, "b": 2, "c": 1}  # no double counting


def test_python_source_resume_filesystem_backend(tmp_path):
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    first = _run_counts(["x", "y"], backend)
    assert first == {"x": 1, "y": 1}
    assert os.path.exists(tmp_path / "pstate" / "streams" / "words.snap")
    second = _run_counts(["x", "y", "x"], backend)
    assert second == {"x": 2, "y": 1}


# ---------------------------------------------------------------------------
# in-process resume: fs source (seek protocol, file-granular offsets)
# ---------------------------------------------------------------------------

def _run_fs_counts(input_dir, backend) -> dict[str, int]:
    G.clear()
    t = pw.io.fs.read(str(input_dir), format="plaintext", mode="batch",
                      autocommit_duration_ms=10, persistent_id="fsrc")
    counts = t.groupby(t.data).reduce(w=t.data, c=pw.reducers.count())
    state: dict[str, int] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["w"]] = row["c"]
        elif state.get(row["w"]) == row["c"]:
            del state[row["w"]]

    pw.io.subscribe(counts, on_change)
    pw.run(persistence_config=pw.persistence.Config.simple_config(backend))
    return state


def test_fs_source_resume_new_files(tmp_path):
    inp = tmp_path / "in"
    inp.mkdir()
    (inp / "a.txt").write_text("w1\nw2\n")
    (inp / "b.txt").write_text("w1\n")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    first = _run_fs_counts(inp, backend)
    assert first == {"w1": 2, "w2": 1}
    # restart with one new file: completed files must not re-emit
    (inp / "c.txt").write_text("w2\nw3\n")
    second = _run_fs_counts(inp, backend)
    assert second == {"w1": 2, "w2": 2, "w3": 1}


def test_fs_source_resume_changed_file(tmp_path):
    inp = tmp_path / "in"
    inp.mkdir()
    (inp / "a.txt").write_text("old1\nold2\n")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    first = _run_fs_counts(inp, backend)
    assert first == {"old1": 1, "old2": 1}
    # file rewritten between runs: replayed rows must be retracted
    (inp / "a.txt").write_text("new1\n")
    os.utime(inp / "a.txt", (time.time() + 5, time.time() + 5))
    second = _run_fs_counts(inp, backend)
    assert second == {"new1": 1}


# ---------------------------------------------------------------------------
# kill-and-recover wordcount (subprocess; tier-4 of SURVEY §4)
# ---------------------------------------------------------------------------

_WORDCOUNT = textwrap.dedent("""
    import sys
    import pathway_tpu as pw

    inp, pdir, out = sys.argv[1], sys.argv[2], sys.argv[3]
    t = pw.io.fs.read(inp, format="plaintext", mode="streaming",
                      autocommit_duration_ms=40, persistent_id="words")
    counts = t.groupby(t.data).reduce(word=t.data, c=pw.reducers.count())
    pw.io.fs.write(counts, out, format="csv")
    pw.run(persistence_config=pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(pdir)))
""")


def _read_counts(out_path) -> dict[str, int]:
    import csv

    state: dict[str, int] = {}
    try:
        with open(out_path, newline="") as f:
            for row in csv.DictReader(f):
                w, c, d = row["word"], int(row["c"]), int(row["diff"])
                if d > 0:
                    state[w] = c
                elif state.get(w) == c:
                    del state[w]
    except (FileNotFoundError, KeyError, ValueError):
        return {}
    return state


@pytest.mark.slow
def test_wordcount_kill_and_recover(tmp_path):
    inp = tmp_path / "in"
    inp.mkdir()
    pdir = str(tmp_path / "pstate")
    out = str(tmp_path / "out.csv")
    script = tmp_path / "wc.py"
    script.write_text(_WORDCOUNT)

    n_files, per_file = 6, 25
    expected: dict[str, int] = {}
    for i in range(3):  # first half of the input exists up-front
        words = [f"w{j % 7}" for j in range(per_file)]
        (inp / f"{i:03d}.txt").write_text("\n".join(words) + "\n")
        for w in words:
            expected[w] = expected.get(w, 0) + 1

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    proc = subprocess.Popen([sys.executable, str(script), str(inp), pdir, out],
                            env=env, cwd="/root/repo")
    try:
        wait_result_with_checker(lambda: _read_counts(out), 60)
        assert _read_counts(out), "no output before kill"
        proc.send_signal(signal.SIGKILL)  # crash mid-stream
        proc.wait()

        for i in range(3, n_files):  # rest of the input arrives after crash
            words = [f"w{j % 5}" for j in range(per_file)]
            (inp / f"{i:03d}.txt").write_text("\n".join(words) + "\n")
            for w in words:
                expected[w] = expected.get(w, 0) + 1

        proc = subprocess.Popen(
            [sys.executable, str(script), str(inp), pdir, out],
            env=env, cwd="/root/repo")
        wait_result_with_checker(
            lambda: _read_counts(out) == expected, 90, step=0.2)
        assert _read_counts(out) == expected

        # SECOND kill/recover cycle (the reference harness kills several
        # times, integration_tests/wordcount/test_recovery.py): crash the
        # recovered process, add more input, recover again — exactly-once
        # across repeated crashes
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        for i in range(n_files, n_files + 2):
            words = [f"w{j % 3}" for j in range(per_file)]
            (inp / f"{i:03d}.txt").write_text("\n".join(words) + "\n")
            for w in words:
                expected[w] = expected.get(w, 0) + 1
        proc = subprocess.Popen(
            [sys.executable, str(script), str(inp), pdir, out],
            env=env, cwd="/root/repo")
        wait_result_with_checker(
            lambda: _read_counts(out) == expected, 90, step=0.2)
        assert _read_counts(out) == expected
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_snapshot_log_rejects_malicious_pickle(tmp_path):
    """Regression: snapshot decode is restricted — a crafted record on
    shared storage must raise, not execute code on resume."""
    import pickle
    import struct
    import zlib

    class Evil:
        def __reduce__(self):
            return (os.system, ("echo pwned > /tmp/pwned",))

    payload = pickle.dumps((1, [Evil()]))
    path = str(tmp_path / "s.snap")
    with open(path, "wb") as f:
        f.write(b"PWSNAP01")
        f.write(struct.pack("<QI", len(payload), zlib.crc32(payload)))
        f.write(payload)
    with pytest.raises(Exception, match="forbidden global"):
        SnapshotLog(path).read_all()


def test_snapshot_log_refuses_alien_format(tmp_path):
    """A file without the format magic must raise — NOT read as empty and
    then get truncated away by the next append."""
    path = str(tmp_path / "s.snap")
    with open(path, "wb") as f:
        f.write(b"some other tool's data that must survive")
    with pytest.raises(ValueError, match="not a PWSNAP01"):
        SnapshotLog(path).read_all()
    with pytest.raises(ValueError, match="not a PWSNAP01"):
        SnapshotLog(path).append(1, [("k", ("v",), 1, None)])
    with open(path, "rb") as f:
        assert f.read() == b"some other tool's data that must survive"


def test_snapshot_log_roundtrips_pandas_datetimes(tmp_path):
    """pd.Timestamp/Timedelta are the engine's host-side datetime values —
    the restricted decoder must admit them or resume self-poisons."""
    import pandas as pd

    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    row = (pd.Timestamp("2026-07-29 12:00"),
           pd.Timestamp("2026-07-29", tz="UTC"),
           pd.Timedelta(seconds=5))
    log.append(1, [("k", row, 1, None)])
    log.close()
    [(_, [(_, got, _, _)])] = SnapshotLog(path).read_all()
    assert got == row


def test_snapshot_log_crc_detects_corruption(tmp_path):
    """A bit-flipped record (and everything after it) is dropped instead of
    being decoded as garbage."""
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    log.append(2, [("k2", ("b",), 1, None)])
    log.close()
    with open(path, "r+b") as f:
        f.seek(-3, os.SEEK_END)  # flip a byte inside the last payload
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    records = SnapshotLog(path).read_all()
    assert [t for t, _ in records] == [1]


def test_snapshot_log_roundtrips_engine_value_types(tmp_path):
    """The restricted decoder must still admit every legitimate engine
    value class: Pointer, Json, numpy arrays, datetimes."""
    import datetime

    import numpy as np

    from pathway_tpu.internals.json import Json
    from pathway_tpu.internals.keys import hash_values

    row = (hash_values("k"), Json({"a": [1, 2]}),
           np.arange(3.0), datetime.datetime(2026, 7, 29, 12, 0),
           datetime.timedelta(seconds=5), b"bytes", ("nested", 1.5))
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(7, [(row[0], row, 1, None)])
    log.close()
    [(t, [(k, got, diff, off)])] = SnapshotLog(path).read_all()
    assert t == 7 and diff == 1 and k == row[0]
    assert isinstance(got[0], type(row[0])) and got[0] == row[0]
    assert got[1].value == {"a": [1, 2]}
    assert np.array_equal(got[2], row[2])
    assert got[3:] == row[3:]


# ---------------------------------------------------------------------------
# crash-recovery edges via the fault-injection harness (testing/faults.py)
# ---------------------------------------------------------------------------

def test_fsync_failure_mid_commit_leaves_loadable_log(tmp_path,
                                                      monkeypatch):
    """An fsync that dies mid-commit with the retry budget disabled must
    surface (the commit is not durable) while leaving the log loadable on
    the next start. (With the default budget a single fsync hiccup is
    retried instead — test_append_retries_* below.)"""
    from pathway_tpu.testing import faults

    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "0")
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    with faults.arm("persistence.fsync", faults.FailNTimes(1)):
        with pytest.raises(faults.InjectedFault):
            log.append(2, [("k2", ("b",), 1, None)])
    log.close()
    # record 1 is durable for sure; record 2 may or may not have reached
    # the platters — either way the log loads and stays appendable
    log2 = SnapshotLog(path)
    times = [t for t, _ in log2.read_all()]
    assert times in ([1], [1, 2])
    log2.append(3, [("k3", ("c",), 1, None)])
    log2.close()
    assert [t for t, _ in SnapshotLog(path).read_all()] == times + [3]


def test_torn_append_drops_tail_and_recovers(tmp_path, monkeypatch):
    """A crash between the record header and its payload (the torn-tail
    shape) with retries disabled is dropped on load, and later appends
    truncate it first."""
    from pathway_tpu.testing import faults

    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "0")
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    with faults.arm("persistence.append.torn", faults.FailNTimes(1)):
        with pytest.raises(faults.InjectedFault):
            log.append(2, [("k2", ("b",), 1, None)])
    log.close()
    assert [t for t, _ in SnapshotLog(path).read_all()] == [1]
    log2 = SnapshotLog(path)
    log2.append(3, [("k3", ("c",), 1, None)])
    log2.close()
    assert [t for t, _ in SnapshotLog(path).read_all()] == [1, 3]


def test_torn_commit_then_rerun_replays_exactly_once(tmp_path):
    """End to end: a commit torn by the armed fault point crashes the run;
    the rerun must drop the torn tail and still count every word exactly
    once."""
    from pathway_tpu.testing import faults

    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    with faults.arm("persistence.append.torn", faults.FailOnHit(2)):
        try:
            _run_counts(["a", "b", "a", "c"], backend)
        except faults.InjectedFault:
            pass  # depending on pacing the fault may hit 0 or 1 commits
    faults.reset()
    state = _run_counts(["a", "b", "a", "c", "b"], backend)
    assert state == {"a": 2, "b": 2, "c": 1}


# ---------------------------------------------------------------------------
# per-partition offset antichains (reference: persistence/frontier.rs:12)
# ---------------------------------------------------------------------------

def test_offset_antichain_fold_and_merge():
    from pathway_tpu.engine.offsets import OffsetAntichain

    a = OffsetAntichain.from_entries([
        ("part", 0, 5), ("part", 1, 2), ("part", 0, 3),  # out of order
        ("row", "file", 0.0, 1, True),                    # non-partitioned
        None,
    ])
    assert a.to_dict() == {0: 5, 1: 2}
    assert a.is_past(0, 5) and a.is_past(0, 1) and not a.is_past(0, 6)
    assert not a.is_past(7, 0)
    b = OffsetAntichain({0: 4, 2: 9})
    assert a.merge(b).to_dict() == {0: 5, 1: 2, 2: 9}


class _PartitionedSource(pw.io.python.PythonSource):
    """Fake Kafka: N partitions of messages; resumes via seek_offsets."""

    def __init__(self, schema, partitions: dict[int, list[str]]):
        class _Subject(pw.io.python.ConnectorSubject):
            def run(self):
                pass

        super().__init__(_Subject(), schema)
        self.partitions = partitions
        self.resumed_from = None

    def seek_offsets(self, antichain) -> None:
        self.resumed_from = antichain

    def run(self, session) -> None:
        seq = 0
        for p, msgs in sorted(self.partitions.items()):
            start = 0
            if self.resumed_from is not None:
                last = self.resumed_from.get(p)
                if last is not None:
                    start = last + 1
            for off in range(start, len(msgs)):
                key, row = self.row_to_engine({"data": msgs[off]}, seq)
                seq += 1
                session.push(key, row, 1, offset=("part", p, off))


def test_partitioned_source_resumes_per_partition(tmp_path):
    """Commit a prefix with different progress per partition, then restart:
    the source must receive the exact per-partition frontier and re-read
    only past it — no duplicates, no loss, no prefix-replay assumption."""
    from pathway_tpu.engine.offsets import OffsetAntichain
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io._datasource import Session

    schema = sch.schema_from_types(data=str)
    storage = str(tmp_path / "snap")
    cfg = pw.persistence.Config(
        backend=pw.persistence.Backend.filesystem(storage))

    # ---- first run: partition 0 commits 2 entries, partition 1 commits 1
    src = _PartitionedSource(schema, {0: ["a0", "a1"], 1: ["b0"]})
    src.persistent_id = "pp"
    driver = PersistenceDriver(cfg)
    live = Session()
    rec = driver.attach_source(src, live)
    k, r = src.row_to_engine({"data": "a0"}, 0)
    rec.push(k, r, 1, offset=("part", 0, 0))
    k, r = src.row_to_engine({"data": "a1"}, 1)
    rec.push(k, r, 1, offset=("part", 0, 1))
    k, r = src.row_to_engine({"data": "b0"}, 2)
    rec.push(k, r, 1, offset=("part", 1, 0))
    driver.commit(1)
    driver.close()

    # ---- restart with MORE data in both partitions
    src2 = _PartitionedSource(
        schema, {0: ["a0", "a1", "a2"], 1: ["b0", "b1"]})
    src2.persistent_id = "pp"
    driver2 = PersistenceDriver(cfg)
    live2 = Session()
    rec2 = driver2.attach_source(src2, live2)
    # replay delivered the durable prefix
    replayed = [row[1][0] for row in live2.drain()]
    assert sorted(replayed) == ["a0", "a1", "b0"]
    # the source got the exact frontier
    assert src2.resumed_from == OffsetAntichain({0: 1, 1: 0})
    # live read continues strictly past it
    src2.run(rec2)
    fresh = [row[1][0] for row in live2.drain()]
    assert sorted(fresh) == ["a2", "b1"]
    driver2.close()


# ---------------------------------------------------------------------------
# transient-write retries (PR 8: internals/retries.py backoff + jitter)
# ---------------------------------------------------------------------------

def test_append_retries_transient_fsync_then_succeeds(tmp_path,
                                                      monkeypatch):
    """A transient fsync failure inside append is retried with backoff
    instead of surfacing — the record lands durably on a later attempt."""
    from pathway_tpu.testing import faults

    monkeypatch.setenv("PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", "1")
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    with faults.arm("persistence.fsync", faults.FailNTimes(2)):
        log.append(1, [("k1", ("a",), 1, None)])  # no raise: 2 < budget 3
    log.append(2, [("k2", ("b",), 1, None)])
    log.close()
    assert [t for t, _ in SnapshotLog(path).read_all()] == [1, 2]


def test_append_retry_truncates_torn_header_before_rewriting(tmp_path,
                                                             monkeypatch):
    """A retried torn append (header written, payload lost) must truncate
    the torn bytes before rewriting — the repaired log contains the
    record exactly once with nothing unreadable in between."""
    from pathway_tpu.testing import faults

    monkeypatch.setenv("PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", "1")
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    with faults.arm("persistence.append.torn", faults.FailNTimes(2)):
        log.append(2, [("k2", ("b",), 1, None)])
    log.append(3, [("k3", ("c",), 1, None)])
    log.close()
    records = SnapshotLog(path).read_all()
    assert [t for t, _ in records] == [1, 2, 3]
    # and the file holds no orphaned torn headers: total size is exactly
    # the three framed records behind the magic
    import struct as _struct

    expect = len(b"PWSNAP01") + sum(
        _struct.calcsize("<QI") + len(__import__("pickle").dumps(
            r, protocol=__import__("pickle").HIGHEST_PROTOCOL))
        for r in records)
    assert os.path.getsize(path) == expect


def test_s3_append_retries_transient_put(monkeypatch):
    """Object-store appends retry a transient PUT failure; the sequence
    number advances only after success (no gap in the prefix)."""
    from pathway_tpu.engine.persistence import S3SnapshotLog

    monkeypatch.setenv("PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", "1")

    class _FlakyClient:
        def __init__(self):
            self.objects: dict[str, bytes] = {}
            self.failures = 2

        def list_objects(self, prefix):
            return [{"key": k} for k in self.objects if k.startswith(prefix)]

        def get_object(self, key):
            return self.objects[key]

        def put_object(self, key, body):
            if self.failures:
                self.failures -= 1
                raise ConnectionError("503 SlowDown")
            self.objects[key] = body

    client = _FlakyClient()
    log = S3SnapshotLog(client, "p", "src")
    log.append(1, [("k1", ("a",), 1, None)])
    log.append(2, [("k2", ("b",), 1, None)])
    records = S3SnapshotLog(client, "p", "src").read_all()
    assert [t for t, _ in records] == [1, 2]


def test_s3_append_retry_exhaustion_raises(monkeypatch):
    """A persistently-failing PUT exhausts the budget and re-raises the
    backend's own exception (the runtime escalates per
    terminate_on_error)."""
    from pathway_tpu.engine.persistence import S3SnapshotLog

    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "1")
    monkeypatch.setenv("PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", "1")

    class _DeadClient:
        def list_objects(self, prefix):
            return []

        def put_object(self, key, body):
            raise ConnectionError("bucket gone")

    log = S3SnapshotLog(_DeadClient(), "p", "src")
    with pytest.raises(ConnectionError, match="bucket gone"):
        log.append(1, [("k1", ("a",), 1, None)])
