"""Update-stream consistency via the DiffEntry harness
(tests/utils.py — reference: python/pathway/tests/utils.py:97-225
DiffEntry + assert_key_entries_in_stream_consistent/assert_stream_equal).

These pin the SHAPE of intermediate emission, not just final state:
which (key, row) pairs appear, with which polarity, in which per-key
order — the contract behaviors/buffers/asof_now are about.
"""

from __future__ import annotations

import pathway_tpu as pw
from pathway_tpu.debug import table_from_rows
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import hash_values
from tests.utils import (
    DiffEntry,
    assert_key_entries_in_stream_consistent,
    assert_stream_equal,
)


def test_streaming_wordcount_exact_update_stream():
    """groupby counts over a 3-tick stream: the per-key stream must be
    exactly +1, -1+2, -2+3 for the repeated word and +1 for the rest."""
    schema = sch.schema_from_types(word=str)
    rows = [("a", 0, 1), ("b", 0, 1), ("a", 2, 1), ("a", 4, 1)]
    t = table_from_rows(schema, rows, is_stream=True)
    counts = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())

    def e(word, order, insertion, c):
        return DiffEntry(hash_values(word), order, insertion,
                         {"word": word, "c": c})

    expected = [
        e("a", 0, True, 1),
        e("a", 1, False, 1), e("a", 2, True, 2),
        e("a", 3, False, 2), e("a", 4, True, 3),
        e("b", 0, True, 1),
    ]
    assert_stream_equal(expected, counts)


def test_windowby_delay_behavior_stream_consistent():
    """Tumbling window with delay: emission may buffer, but whatever
    surfaces per window must be a subsequence of the expected revision
    chain ending at the final sums (temporal-behavior site for the
    DiffEntry harness)."""
    schema = sch.schema_from_types(sensor=str, v=int, at=int)
    rows = [
        ("s1", 1, 0, 2, 1), ("s1", 2, 1, 2, 1),   # window [0,4): 1+2
        ("s1", 4, 5, 4, 1),                        # window [4,8): 4
        ("s1", 8, 2, 6, 1),                        # late row into [0,4)
    ]
    t = table_from_rows(schema, rows, is_stream=True)
    win = pw.temporal.windowby(
        t, t.at, window=pw.temporal.tumbling(4), instance=t.sensor,
        behavior=pw.temporal.common_behavior(delay=2),
    ).reduce(
        sensor=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )

    def window_key(sensor, start, end):
        # WindowedTable.reduce groups by (window, start, end, instance)
        # with window = (instance, start, end)
        return hash_values((sensor, start, end), start, end, sensor)

    def e(sensor, start, end, order, insertion, s):
        return DiffEntry(window_key(sensor, start, end), order, insertion,
                         {"sensor": sensor, "start": start, "s": s})

    expected = [
        # [0,4): may surface 3 (before the late row) then revise to 11
        e("s1", 0, 4, 0, True, 3),
        e("s1", 0, 4, 1, False, 3), e("s1", 0, 4, 2, True, 11),
        # [4,8): single emission of 4
        e("s1", 4, 8, 0, True, 4),
    ]
    assert_key_entries_in_stream_consistent(expected, win)


def test_asof_now_join_stream_consistent():
    """asof_now: each query joins the dimension state AS OF its arrival
    and is never revised — the per-query stream must be exactly one
    insertion carrying the state visible at that tick."""
    dim_schema = sch.schema_from_types(k=str, label=str)
    dims = table_from_rows(
        dim_schema, [("x", "old", 0, 1), ("x", "old", 2, -1),
                     ("x", "new", 2, 1)], is_stream=True)
    q_schema = sch.schema_from_types(k=str, qid=int)
    queries = table_from_rows(
        q_schema, [("x", 1, 1, 1), ("x", 2, 3, 1)], is_stream=True)
    queries = queries.with_id_from(queries.qid)

    joined = pw.temporal.asof_now_join(
        queries, dims, queries.k == dims.k, id=queries.id,
    ).select(qid=queries.qid, label=dims.label)

    def e(qid, order, insertion, label):
        return DiffEntry(hash_values(qid), order, insertion,
                         {"qid": qid, "label": label})

    expected = [
        e(1, 0, True, "old"),   # query at t=1 sees the original label
        e(2, 0, True, "new"),   # query at t=3 sees the replacement
    ]
    assert_stream_equal(expected, joined)
