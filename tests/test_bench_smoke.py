"""bench.py must stay runnable — the driver executes it on real hardware
at round end; a silent import/shape regression there would void the
round's measurements. CPU-sized smoke of each leg's machinery."""

from __future__ import annotations

import sys

import pytest


@pytest.fixture(autouse=True)
def _clear():
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    yield
    G.clear()


def test_bench_imports_and_docs():
    sys.path.insert(0, "/root/repo")
    import bench

    docs = bench.make_docs(64)
    assert len(docs) == 64 and all(isinstance(d, str) for d in docs)


def test_bench_etl_leg_small():
    import bench

    out = bench.bench_etl(4000)
    assert out["etl_rows_per_s_1w"] > 0
    assert out["etl_rows_per_s_8w"] > 0
    assert out["etl_n_cores"] >= 1


def test_bench_emits_json_even_when_backend_is_dead():
    """Round-3 regression: a backend failure must still yield ONE parseable
    JSON line with an ``error`` field plus completed legs — not rc=1."""
    import json
    import os
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="bogus", BENCH_SKIP="etl",
               BENCH_PROBE_TIMEOUT="30", BENCH_PROBE_WINDOW="20")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-u", "/root/repo/bench.py"],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr[-500:]
    line = [ln for ln in proc.stdout.splitlines() if ln.strip()][-1]
    out = json.loads(line)
    assert "error" in out and out["unit"] == "docs/s"


def test_graft_dryrun_provisions_cpu_before_device_touch():
    """Round-3 regression: _provision_devices must never initialize the
    real TPU backend (an unhealthy tunnel hangs forever in PJRT setup)."""
    import pathlib

    src = pathlib.Path("/root/repo/__graft_entry__.py").read_text()
    body = src.split("def _provision_devices", 1)[1].split("\ndef ", 1)[0]
    body = body.split('"""')[2]  # code after the docstring
    assert body.index("jax.config.update") < body.index("jax.devices()")


def test_bench_tokenizer_and_encoder_shapes():
    """The embed leg's host-side pieces: WordPiece batch + bucketing pack
    produce shapes the jitted encoder accepts."""
    import numpy as np

    import bench
    from pathway_tpu.models.tokenizer import (WordPieceTokenizer,
                                              make_synthetic_vocab)

    tok = WordPieceTokenizer(
        make_synthetic_vocab([f"word{i}" for i in range(512)],
                             vocab_size=30522), max_len=bench.SEQ)
    docs = bench.make_docs(8)
    ids, mask = tok.batch(docs, pad_to=bench.SEQ)
    assert ids.shape == (8, bench.SEQ) and mask.shape == ids.shape
    lens = mask.sum(axis=1)
    assert (lens > 0).all()
    # pack() logic: int16 ids + bucket width multiple of 16
    width = min(bench.SEQ, max(16, int(-(-int(lens.max()) // 16) * 16)))
    assert width % 16 == 0 and ids[:, :width].astype(np.int16).dtype == \
        np.int16
