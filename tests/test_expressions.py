"""Expression system (reference: engine Expression ops, engine.pyi:211-390)."""

import asyncio

import numpy as np
import pytest

import pathway_tpu as pw
from tests.utils import T, rows_of


def test_if_else_coalesce_require():
    t = T("""
    a | b
    1 |
    2 | 5
    """)
    r = t.select(
        c=pw.if_else(t.a > 1, t.a, 0),
        d=pw.coalesce(t.b, t.a),
        e=pw.require(t.a + 1, t.b),
    )
    assert sorted(rows_of(r), key=repr) == [(0, 1, None), (2, 5, 3)]


def test_str_namespace():
    t = T("""
    s
    'Hello World'
    """)
    r = t.select(
        lo=t.s.str.lower(),
        ln=t.s.str.len(),
        sw=t.s.str.startswith("Hello"),
        rep=t.s.str.replace("World", "TPU"),
    )
    assert rows_of(r) == [("hello world", 11, True, "Hello TPU")]


def test_parse_numbers():
    t = T("""
    s
    '42'
    """)
    r = t.select(i=t.s.str.parse_int(), f=t.s.str.parse_float())
    assert rows_of(r) == [(42, 42.0)]


def test_dt_namespace():
    t = T("""
    s
    '2023-03-25 12:30:15'
    """)
    d = t.select(dt=t.s.dt.strptime("%Y-%m-%d %H:%M:%S"))
    r = d.select(y=d.dt.dt.year(), m=d.dt.dt.month(), h=d.dt.dt.hour())
    assert rows_of(r) == [(2023, 3, 12)]


def test_duration_arithmetic():
    t = T("""
    a              | b
    '2023-01-02'   | '2023-01-01'
    """)
    d = t.select(
        x=t.a.dt.strptime("%Y-%m-%d"),
        y=t.b.dt.strptime("%Y-%m-%d"),
    )
    r = d.select(days=(d.x - d.y).dt.days())
    assert rows_of(r) == [(1,)]


def test_apply_and_udf():
    t = T("""
    a
    1
    2
    """)

    @pw.udf
    def double(x: int) -> int:
        return x * 2

    r = t.select(b=pw.apply(lambda x: x + 10, t.a), c=double(t.a))
    assert sorted(rows_of(r)) == [(11, 2), (12, 4)]


def test_async_udf():
    t = T("""
    a
    1
    2
    """)

    @pw.udf
    async def slow_double(x: int) -> int:
        await asyncio.sleep(0.001)
        return x * 2

    r = t.select(b=slow_double(t.a))
    assert sorted(rows_of(r)) == [(2,), (4,)]


def test_udf_cache_and_retries():
    calls = []

    @pw.udf(cache_strategy=pw.InMemoryCache(), deterministic=True)
    def f(x: int) -> int:
        calls.append(x)
        return x + 1

    t = T("""
    a
    5
    5
    """)
    r = t.select(b=f(t.a))
    assert rows_of(r) == [(6,), (6,)]
    assert len(calls) == 1  # second call served from cache


def test_error_and_fill_error():
    t = T("""
    a | b
    1 | 0
    4 | 2
    """)
    r = t.select(c=pw.fill_error(t.a // t.b, -1))
    assert sorted(rows_of(r)) == [(-1,), (2,)]


def test_make_tuple_and_get():
    t = T("""
    a | b
    1 | 2
    """)
    r = t.select(t3=pw.make_tuple(t.a, t.b, t.a + t.b))
    r2 = r.select(x=r.t3[2], y=r.t3.get(10, default=-1))
    assert rows_of(r2) == [(3, -1)]


def test_json():
    t = T("""
    a
    1
    """)
    j = pw.Json({"x": {"y": [1, 2, 3]}})
    r = t.select(v=pw.apply_with_type(lambda _: j["x"]["y"][1].as_int(), int, t.a))
    assert rows_of(r) == [(2,)]


def test_matmul_on_arrays():
    t = T("""
    a
    1
    """)
    m = np.eye(2)
    v = np.array([3.0, 4.0])
    r = t.select(x=pw.apply_with_type(lambda _: float((m @ v)[1]), float, t.a))
    assert rows_of(r) == [(4.0,)]


def test_pointer_from_stable():
    t = T("""
    a
    1
    2
    """)
    r = t.select(p1=t.pointer_from(t.a), p2=pw.this.pointer_from(pw.this.a))
    for p1, p2 in rows_of(r):
        assert p1 == p2


def test_ndarray_cells_roundtrip():
    t = T("""
    a
    1
    """)
    r = t.select(v=pw.apply(lambda x: np.arange(3) * x, t.a))
    r2 = r.select(s=pw.apply_with_type(lambda v: float(v.sum()), float, r.v))
    assert rows_of(r2) == [(3.0,)]


def test_numeric_fast_path_keeps_python_semantics():
    """The vectorized numeric BinaryExpression path must be bit-compatible
    with per-row python evaluation: bigint precision, mixed int/float,
    comparisons, and ERROR cells falling back."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from tests.utils import rows_of

    big = (1 << 62) + 7
    t = table_from_rows(
        sch.schema_from_types(a=int, b=int, f=float),
        [(big, big, 0.5), (3, 4, 1.5), (-5, 2, -2.0)] + [
            (i, i + 1, float(i)) for i in range(100, 120)])
    out = t.select(
        s=t.a + t.b, p=t.a * t.b, lt=t.a < t.b, mixed=t.a + t.f)
    rows = dict()
    for s, p, lt, mixed in rows_of(out):
        rows[s] = (p, lt, mixed)
    # bigint addition/multiplication stayed exact (no int64 wrap)
    assert rows[2 * big] == (big * big, False, big + 0.5)
    assert rows[7] == (12, True, 3 + 1.5)


def test_numeric_fast_path_edge_semantics():
    """Edges the vectorized path must fall back on: elementwise ==/!=,
    INT64_MIN magnitudes, and >2^53 ints compared against floats."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from tests.utils import rows_of

    int64_min = -(1 << 63)
    huge = (1 << 53) + 1
    t = table_from_rows(
        sch.schema_from_types(a=int, b=int, f=float),
        [(int64_min, 2, 1.0), (huge, huge, float(1 << 53))] + [
            (i, i, float(i)) for i in range(100, 110)])
    out = t.select(
        eq=t.a == t.b, ne=t.a != t.b, d=t.a - t.b, gt=t.a > t.f)
    got = sorted(rows_of(out))
    # INT64_MIN subtraction stays exact python arithmetic
    assert (False, True, int64_min - 2, False) in got
    # 2^53+1 > 2^53 float: exact int/float comparison (numpy would round)
    assert (True, False, 0, True) in got
    # elementwise equality over the plain range rows
    assert got.count((True, False, 0, False)) == 10


def test_division_family_fast_path_semantics():
    """//, % and / ride the vectorized path with python semantics intact:
    floor toward -inf, % sign follows the divisor, int/int division is
    exact, and any zero divisor falls back to per-row evaluation (ERROR
    cells, not numpy's warn-and-0/inf)."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from tests.utils import rows_of

    t = table_from_rows(
        sch.schema_from_types(a=int, b=int, f=float),
        [(-7, 2, 2.5), (7, -2, -2.5), ((1 << 53) + 1, 3, 0.5)] + [
            (i * 37, i % 9 + 1, float(i) + 0.5) for i in range(100)])
    out = t.select(
        a=t.a, fd=t.a // t.b, md=t.a % t.b, td=t.a / t.b, ffd=t.a // t.f)
    got = {r[0]: tuple(r[1:]) for r in rows_of(out)}
    assert got[-7] == (-4, 1, -3.5, -7 // 2.5)   # floor toward -inf
    assert got[7] == (-4, -1, -3.5, 7 // -2.5)   # % follows divisor
    big = (1 << 53) + 1
    assert got[big] == (big // 3, big % 3, big / 3, big // 0.5)
    for i in range(100):
        a, b, f = i * 37, i % 9 + 1, float(i) + 0.5
        assert got[a] == (a // b, a % b, a / b, a // f)

    # zero divisor: per-row fallback turns the bad cells into ERROR while
    # the good cells still compute
    tz = table_from_rows(
        sch.schema_from_types(a=int, b=int),
        [(10, 2)] * 20 + [(10, 0)])
    outz = tz.select(d=tz.a // tz.b)
    vals = [r[0] for r in rows_of(outz)]
    assert vals.count(5) == 20
    assert len(vals) == 21  # the zero-divisor row became an ERROR cell


def test_ifelse_and_negation_fast_paths():
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from tests.utils import rows_of

    int64_min = -(1 << 63)
    t = table_from_rows(
        sch.schema_from_types(a=int, b=int),
        [(int64_min, 1), (5, 2)] + [(i, i % 3) for i in range(100, 110)])
    out = t.select(
        neg=-t.a,                       # INT64_MIN negation stays exact
        pick=pw.if_else(t.a > t.b, t.a, t.b),
        # mixed int/float branches keep per-row types (fallback path)
        mixed=pw.if_else(t.a > t.b, t.a, t.b * 0.5),
    )
    got = {r[0]: r for r in rows_of(out)}
    assert got[-int64_min][0] == -int64_min          # python bigint
    assert got[-5] == (-5, 5, 5)
    assert got[-100] == (-100, 100, 100)
    assert isinstance(got[-5][2], int)               # per-row type kept
    weird = table_from_rows(
        sch.schema_from_types(a=int, b=int),
        [(1, 3)] + [(i, 1) for i in range(10)])
    m = weird.select(v=pw.if_else(weird.a > weird.b, weird.a, weird.b * 0.5))
    vals = [r[0] for r in rows_of(m)]
    assert 0.5 in vals and isinstance(sorted(vals)[-1], (int, float))


def test_fast_paths_reject_lca_widened_float_columns_with_runtime_ints():
    """A statically-FLOAT column can hold python ints (types_lca); the
    vectorized paths must fall back so >2^53 ints stay exact and keep
    their per-row types (review r4 finding)."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from tests.utils import rows_of

    huge = (1 << 53) + 1
    # mixed: FLOAT-typed column whose values are python ints and floats
    t = table_from_rows(
        sch.schema_from_types(c=bool, x=float, y=float),
        [(True, huge, 0.5), (False, 3, 2.5)] + [
            (bool(i % 2), float(i), float(i)) for i in range(100, 110)])
    out = t.select(
        n=-t.x,
        sel=pw.if_else(t.c, t.x, t.y),
        cmp=t.x > t.y,
    )
    got = {r[0]: r for r in rows_of(out)}
    # huge int stays an exact int through negation and selection
    assert got[-huge] == (-huge, huge, True)
    assert isinstance(got[-huge][1], int)
    assert got[-3] == (-3, 2.5, True)
    assert isinstance(got[-3][0], int)
