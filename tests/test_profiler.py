"""Continuous profiling plane (engine/profiler.py):

- the analytic cost model pins: FLOPs and bytes per kernel family match
  hand-computed values at known shapes, and bench.py's MFU math goes
  through the SAME encoder formula (no drift between the live gauges
  and the benchmark);
- roofline classification: arithmetic intensity vs machine balance
  decides compute- vs bandwidth-bound, honoring the BENCH_* env
  overrides bench.py honors;
- leg attribution: dispatches buffered inside a bridge leg are
  re-scaled pro-rata to the leg's MEASURED execute time (and a failed
  leg falls back to call-site walls, unattributed);
- the host sampler emits well-formed collapsed-flamegraph text with
  thread roles from the uniform pathway-tpu-* inventory, tags samples
  with the flight recorder's in-flight operator, and windowed baselines
  subtract correctly;
- the knn hooks record search/scatter dispatches without perturbing
  results — profiler-on output equals profiler-off output exactly;
- per-tenant serving metrics: attribute_tenant + tenant_summary expose
  per-tenant p50/p95 and an SLO burn rate per tenant;
- profdiff names the dominant kernel/frame delta between two profiles.
"""

from __future__ import annotations

import re
import threading
import time

import numpy as np
import pytest

from pathway_tpu.engine.profiler import (Profiler, current_profiler,
                                         diff_profiles, encoder_cost,
                                         encoder_flops_per_token,
                                         ingest_scatter_cost,
                                         install_profiler, knn_search_cost,
                                         live_profiler_stats,
                                         machine_balance, machine_params,
                                         segment_attention_cost)


@pytest.fixture(autouse=True)
def _fresh_profiler():
    install_profiler(None)
    yield
    install_profiler(None)


# ---------------------------------------------------------------------------
# analytic cost model: hand-computed pins
# ---------------------------------------------------------------------------

def test_encoder_flops_per_token_pin():
    # h=64 f=128 L=2 S=16:
    #   per_layer = 2*(4*64*64 + 2*64*128) = 2*(16384+16384) = 65536
    #   attn      = 2*4*16*64 = 8192
    #   total     = 2*65536 + 8192 = 139264
    assert encoder_flops_per_token(64, 128, 2, 16) == 139264.0


def test_encoder_cost_pin():
    # B=1 S=4 h=8 f=16 L=1:
    #   fpt   = 2*(4*64 + 2*8*16) + 1*4*4*8 = 1024 + 128 = 1152
    #   flops = 1*4*1152 = 4608
    #   param = 2*(4*64 + 2*8*16) = 1024 bytes (bf16)
    #   act   = 8*1*(2*1*4*8) = 512;  emb = 2*1*4*8 = 64
    flops, nbytes = encoder_cost(1, 4, hidden=8, intermediate=16, layers=1)
    assert flops == 4608.0
    assert nbytes == 1024.0 + 512.0 + 64.0


def test_segment_attention_adds_score_tensor_bytes():
    base_f, base_b = encoder_cost(1, 4, hidden=8, intermediate=16, layers=1)
    seg_f, seg_b = segment_attention_cost(1, 4, hidden=8, intermediate=16,
                                          layers=1)
    assert seg_f == base_f  # same matmul tree, mask changes nothing
    # score tensor: 2 (write+read) * L * 2 bytes * B * S * S = 64
    assert seg_b == base_b + 64.0


def test_knn_search_cost_pin():
    # Q=4 N=1024 D=64 f32: flops = 2*4*1024*64 = 524288
    #   bytes = 1024*64*4 (slab) + 4*64*4 (queries) = 262144 + 1024
    assert knn_search_cost(4, 1024, 64) == (524288.0, 263168.0)
    # int8 slab carries f32 scales+vsq side columns (8 B/row)
    flops, nbytes = knn_search_cost(2, 100, 32, itemsize=1,
                                    extra_row_bytes=8)
    assert flops == 2.0 * 2 * 100 * 32
    assert nbytes == 100 * (32 + 8) + 2 * 32 * 4


def test_ingest_scatter_cost_pin():
    # read f32 in + write slab row at storage width
    assert ingest_scatter_cost(8, 16) == (256.0, 8 * 16 * 8.0)
    assert ingest_scatter_cost(8, 16, itemsize=1)[1] == 8 * 16 * 5.0


def test_machine_balance_default_and_env(monkeypatch):
    monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)
    monkeypatch.delenv("BENCH_HBM_GBPS", raising=False)
    assert machine_params() == {"peak_tflops": 197.0, "hbm_gbps": 819.0}
    assert machine_balance() == pytest.approx(197e12 / 819e9)
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "100")
    monkeypatch.setenv("BENCH_HBM_GBPS", "1000")
    assert machine_balance() == pytest.approx(100.0)  # 100e12 / 1000e9


def test_bench_mfu_uses_shared_encoder_formula():
    """bench.py's per-token FLOPs must be THE shared formula — a drift
    here silently decouples the live MFU gauge from the benchmark."""
    import sys

    sys.path.insert(0, "/root/repo")
    import bench

    from pathway_tpu.models.encoder import EncoderConfig
    cfg = EncoderConfig(hidden=64, intermediate=128, layers=2)
    assert bench._encoder_flops_per_token(cfg, seq=16) == \
        encoder_flops_per_token(64, 128, 2, 16)
    assert bench.PEAK_TFLOPS == machine_params()["peak_tflops"]


def test_encoder_cost_helper_routes_ragged():
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.models.encoder import encoder_cost as model_cost

    cfg = EncoderConfig(hidden=8, intermediate=16, layers=1)
    assert model_cost(cfg, 1, 4) == encoder_cost(
        1, 4, hidden=8, intermediate=16, layers=1)
    assert model_cost(cfg, 1, 4, ragged=True) == segment_attention_cost(
        1, 4, hidden=8, intermediate=16, layers=1)


# ---------------------------------------------------------------------------
# roofline classification + rolling gauges
# ---------------------------------------------------------------------------

def test_roofline_classification():
    prof = Profiler(sample_interval_ms=1e6)
    # knn search: AI = 2Q/itemsize ≈ 2 FLOP/byte at Q=4 — far below
    # machine balance → bandwidth-bound
    f, b = knn_search_cost(4, 1024, 64)
    prof.record_dispatch("knn_search", f, b, 2.0)
    # synthetic compute-bound family: AI far above balance
    prof.record_dispatch("encoder_forward", 1e12, 1e6, 5.0)
    fams = prof.family_stats()
    knn = fams["knn_search"]["roofline"]
    assert knn["bound_by"] == "bandwidth"
    assert knn["arithmetic_intensity"] == pytest.approx(f / b, rel=1e-3)
    assert 0.0 < knn["attainable_mfu"] < 1.0
    enc = fams["encoder_forward"]["roofline"]
    assert enc["bound_by"] == "compute"
    assert enc["attainable_mfu"] == 1.0
    # rolling gauges aggregate across families
    assert prof.rolling_mfu() > 0.0
    assert prof.rolling_hbm_bw_util() > 0.0


def test_rolling_mfu_matches_hand_computation(monkeypatch):
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "1")  # 1e12 FLOP/s peak
    prof = Profiler(sample_interval_ms=1e6)
    prof.record_dispatch("knn_search", 5e8, 1e6, 1.0)  # 5e8 FLOP in 1ms
    # 5e8 / 1e-3 s = 5e11 FLOP/s → 50% of the 1e12 peak
    assert prof.rolling_mfu() == pytest.approx(0.5, rel=1e-6)


# ---------------------------------------------------------------------------
# leg attribution: measured bridge time re-scales buffered dispatches
# ---------------------------------------------------------------------------

def test_leg_attribution_rescales_to_measured_time():
    prof = Profiler(sample_interval_ms=1e6)
    prof.begin_leg(tick=3)
    # two async dispatches that "returned" in ~0 host ms: the leg's
    # measured 10ms must be split by analytic bytes (3:1)
    prof.record_dispatch("knn_search", 100.0, 3000.0, 0.001)
    prof.record_dispatch("ingest_scatter", 50.0, 1000.0, 0.001)
    prof.end_leg(10.0)
    fams = prof.family_stats()
    assert fams["knn_search"]["device_ms_total"] == pytest.approx(7.5)
    assert fams["ingest_scatter"]["device_ms_total"] == pytest.approx(2.5)
    assert fams["knn_search"]["attributed_dispatches"] == 1
    assert fams["ingest_scatter"]["attributed_dispatches"] == 1
    total = sum(f["device_ms_total"] for f in fams.values())
    assert total == pytest.approx(10.0)  # sums exactly to the leg


def test_leg_attribution_prefers_meaningful_walls():
    prof = Profiler(sample_interval_ms=1e6)
    prof.begin_leg(tick=0)
    # blocking call sites: their own walls carry the signal (8ms vs 2ms)
    prof.record_dispatch("knn_search", 1.0, 1.0, 8.0)
    prof.record_dispatch("ingest_scatter", 1.0, 1.0, 2.0)
    prof.end_leg(20.0)
    fams = prof.family_stats()
    assert fams["knn_search"]["device_ms_total"] == pytest.approx(16.0)
    assert fams["ingest_scatter"]["device_ms_total"] == pytest.approx(4.0)


def test_failed_leg_keeps_callsite_walls_unattributed():
    prof = Profiler(sample_interval_ms=1e6)
    prof.begin_leg(tick=0)
    prof.record_dispatch("knn_search", 10.0, 10.0, 1.25)
    prof.end_leg(None)  # leg raised
    fams = prof.family_stats()
    assert fams["knn_search"]["device_ms_total"] == pytest.approx(1.25)
    assert fams["knn_search"]["attributed_dispatches"] == 0


def test_record_outside_leg_commits_immediately():
    prof = Profiler(sample_interval_ms=1e6)
    prof.record_dispatch("encoder_forward", 10.0, 10.0, 4.0)
    fams = prof.family_stats()
    assert fams["encoder_forward"]["dispatches"] == 1
    assert fams["encoder_forward"]["attributed_dispatches"] == 0
    assert fams["encoder_forward"]["device_ms_total"] == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# host sampler: collapsed grammar, roles, in-flight tags, baselines
# ---------------------------------------------------------------------------

_COLLAPSED_LINE = re.compile(r"^[^; ][^;]*(;[^;]+)* \d+$")


def _busy_engine_thread(stop: threading.Event):
    def _inner_hot_loop():
        x = 0.0
        while not stop.is_set():
            x += 1.0
        return x

    _inner_hot_loop()


def test_sampler_collapsed_grammar_and_thread_roles():
    from pathway_tpu.engine import threads

    stop = threading.Event()
    t = threads.spawn(_busy_engine_thread, args=(stop,), name="test-busy")
    prof = Profiler(sample_interval_ms=2.0)
    try:
        prof.start()
        deadline = time.monotonic() + 5.0
        while prof.samples_total < 10 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        prof.stop()
        stop.set()
        t.join(5.0)
    assert prof.samples_total >= 10
    text = prof.collapsed()
    lines = text.strip().splitlines()
    assert lines, "no folded stacks collected"
    for ln in lines:
        assert _COLLAPSED_LINE.match(ln), f"bad collapsed line: {ln!r}"
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert counts == sorted(counts, reverse=True)
    roles = {ln.split(";", 1)[0] for ln in lines}
    assert "test-busy" in roles  # pathway-tpu- prefix stripped to role
    # the busy thread's hot frame is in its folded stack
    busy = [ln for ln in lines if ln.startswith("test-busy;")]
    assert any("_inner_hot_loop" in ln for ln in busy)
    # the sampler never profiles itself into the profile
    assert "profiler-sampler" not in roles
    assert prof.top_host_frame() is not None


def test_sampler_tags_inflight_device_leg(monkeypatch):
    from pathway_tpu.engine import threads
    from pathway_tpu.engine import flight_recorder as fr

    stop = threading.Event()
    t = threads.spawn(_busy_engine_thread, args=(stop,), name="device-bridge")
    try:
        deadline = time.monotonic() + 2.0
        while t.ident is None and time.monotonic() < deadline:
            time.sleep(0.005)
        ident = t.ident
        monkeypatch.setattr(fr, "live_inflight_by_thread",
                            lambda: {ident: ("device", "knn_q")})
        prof = Profiler(sample_interval_ms=2.0)
        try:
            prof.start()
            deadline = time.monotonic() + 5.0
            while (prof.device_attributed_samples < 3
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        finally:
            prof.stop()
    finally:
        stop.set()
        t.join(5.0)
    assert prof.device_attributed_samples >= 3
    assert "[device:knn_q]" in prof.collapsed()


def test_collapsed_baseline_subtracts_prior_samples():
    prof = Profiler(sample_interval_ms=1e6)
    with prof._lock:
        prof._stacks[("worker", ("f (a.py:1)",))] = 7
    baseline = prof.stack_counts()
    with prof._lock:
        prof._stacks[("worker", ("f (a.py:1)",))] = 9
        prof._stacks[("worker", ("g (a.py:2)",))] = 1
    diff = prof.collapsed(baseline)
    assert "worker;f (a.py:1) 2" in diff
    assert "worker;g (a.py:2) 1" in diff
    assert "7" not in diff  # absolute counts never leak into the window


def test_stack_table_overflow_folds_into_other_bucket():
    from pathway_tpu.engine import profiler as mod

    prof = Profiler(sample_interval_ms=1e6)
    with prof._lock:
        for i in range(mod._MAX_DISTINCT_STACKS):
            prof._stacks[("worker", (f"f{i} (x.py:{i})",))] = 1
    # simulate the sampler seeing a brand-new stack past the bound
    key = ("worker", ("fresh (y.py:1)",))
    with prof._lock:
        if key in prof._stacks:
            prof._stacks[key] += 1
        elif len(prof._stacks) < mod._MAX_DISTINCT_STACKS:
            prof._stacks[key] = 1
        else:
            other = (key[0], ("(other)",))
            prof._stacks[other] = prof._stacks.get(other, 0) + 1
    assert prof.stack_counts().get(("worker", ("(other)",))) == 1


def test_live_profiler_stats_roundtrip():
    assert live_profiler_stats() is None
    prof = Profiler(sample_interval_ms=1e6)
    install_profiler(prof)
    assert current_profiler() is prof
    st = live_profiler_stats()
    assert st is not None
    assert set(st) >= {"host", "machine", "mfu_rolling", "hbm_bw_util",
                       "families", "capture"}
    assert st["host"]["sampling"] is False
    assert st["machine"]["balance_flop_per_byte"] == pytest.approx(
        machine_balance(), abs=1e-3)


# ---------------------------------------------------------------------------
# knn hooks: dispatches recorded, outputs byte-identical on/off
# ---------------------------------------------------------------------------

def _knn_roundtrip(n=48, dim=8, q=3):
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    rng = np.random.default_rng(7)
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    idx = BruteForceKnnIndex(dim, metric=KnnMetric.L2SQ, paged=False)
    idx.add_batch([Pointer(i) for i in range(n)], vecs)
    queries = [(Pointer(1000 + i), vecs[i * 5], 4, None) for i in range(q)]
    return idx.search(queries)


@pytest.mark.slow
def test_knn_outputs_identical_with_profiler_on_and_off():
    off = _knn_roundtrip()
    prof = Profiler(sample_interval_ms=1e6)
    install_profiler(prof)
    on = _knn_roundtrip()
    assert on == off  # the profiler only observes shapes and clocks
    fams = prof.family_stats()
    assert fams["ingest_scatter"]["dispatches"] >= 1
    assert fams["knn_search"]["dispatches"] >= 1
    assert fams["knn_search"]["roofline"]["bound_by"] == "bandwidth"
    # search bytes follow the slab-scan model exactly: N*D*4 + Q*D*4
    # per dispatch, with N the (power-of-two) device capacity
    from pathway_tpu.engine.profiler import knn_search_cost as cost

    per = fams["knn_search"]["bytes_total"] / \
        fams["knn_search"]["dispatches"]
    caps = [cost(3, 1 << p, 8)[1] for p in range(4, 12)]
    assert per in caps


@pytest.mark.slow
def test_paged_knn_records_families_too():
    # the paged store (default since PR 7) overrides _scatter and
    # _device_topk — the production serving path must feed the cost
    # model like the legacy slab does (regression: a live server on
    # paged storage exported zero kernel families)
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    prof = Profiler(sample_interval_ms=1e6)
    install_profiler(prof)
    rng = np.random.default_rng(7)
    vecs = rng.normal(size=(48, 8)).astype(np.float32)
    idx = BruteForceKnnIndex(8, metric=KnnMetric.L2SQ, paged=True)
    idx.add_batch([Pointer(i) for i in range(48)], vecs)
    out = idx.search([(Pointer(1000), vecs[5], 4, None)])
    assert out and out[0]
    fams = prof.family_stats()
    assert fams["ingest_scatter"]["dispatches"] >= 1
    assert fams["knn_search"]["dispatches"] >= 1
    assert fams["knn_search"]["roofline"]["bound_by"] == "bandwidth"
    assert fams["knn_search"]["device_ms_total"] > 0.0


# ---------------------------------------------------------------------------
# per-tenant serving metrics (engine/request_tracker.py)
# ---------------------------------------------------------------------------

def _finish_query(tr, rid, key, ms, tenant=None):
    # finish() stamps t_responded with the real clock, so the synthetic
    # span must live on it too: e2e ends up ≈ ms (normalized_stamps
    # snaps the response stamp up to t_resolved)
    base = time.perf_counter()
    span = tr.start(rid, "/q", t_ingress=base)
    span.key = key
    tr._by_key[key] = span
    span.t_enqueued = base
    if tenant is not None:
        tr.attribute_tenant([key], tenant)
    span.t_resolved = base + ms / 1e3
    tr.finish(span)


def test_tenant_summary_tracks_per_tenant_quantiles_and_burn():
    from pathway_tpu.engine.request_tracker import RequestTracker

    tr = RequestTracker(slo_ms=50.0)
    for i in range(40):
        _finish_query(tr, f"a{i}", ("a", i), 10.0, tenant="acme")
    for i in range(40):
        _finish_query(tr, f"b{i}", ("b", i), 100.0, tenant="bigco")
    for i in range(5):
        _finish_query(tr, f"n{i}", ("n", i), 10.0)  # unattributed
    ts = tr.tenant_summary()
    assert set(ts) == {"acme", "bigco"}
    assert ts["acme"]["count"] == 40
    assert ts["acme"]["p50_ms"] <= ts["acme"]["p95_ms"]
    # acme is inside SLO, bigco burns budget every query
    assert ts["acme"]["burn_rate"] == 0.0
    assert ts["bigco"]["burn_rate"] > 1.0
    assert tr.summary()["tenants"] == ts


def test_attribute_tenant_first_attribution_wins():
    from pathway_tpu.engine.request_tracker import RequestTracker

    tr = RequestTracker(slo_ms=50.0)
    span = tr.start("r1", "/q", t_ingress=0.0)
    span.key = "k1"
    tr._by_key["k1"] = span
    tr.attribute_tenant(["k1", "missing-key"], "first")
    tr.attribute_tenant(["k1"], "second")
    assert span.tenant == "first"


def test_knn_search_attributes_tenant_to_live_trackers():
    from pathway_tpu.engine.request_tracker import RequestTracker
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    tr = RequestTracker(slo_ms=50.0)  # registers itself in _LIVE
    qkey = Pointer(501)
    span = tr.start("r1", "/q", t_ingress=0.0)
    span.key = qkey
    tr._by_key[qkey] = span
    idx = BruteForceKnnIndex(4, metric=KnnMetric.L2SQ, paged=False)
    idx._tenant = "acme"
    idx.add_batch([Pointer(0)], np.ones((1, 4), np.float32))
    idx.search([(qkey, np.ones(4, np.float32), 1, None)])
    assert span.tenant == "acme"


# ---------------------------------------------------------------------------
# profdiff: naming the dominant regressor
# ---------------------------------------------------------------------------

def _epoch(knn_ms, frame_share, samples=100):
    return {
        "mfu_rolling": 0.1,
        "families": {
            "knn_search": {"dispatches": 10,
                           "device_ms_total": knn_ms * 10,
                           "roofline": {"bound_by": "bandwidth"}},
            "encoder_forward": {"dispatches": 10, "device_ms_total": 50.0,
                                "roofline": {"bound_by": "compute"}},
        },
        "host": {
            "samples_total": samples,
            "top_frames": [
                {"frame": "search (knn.py:900)",
                 "samples": int(samples * frame_share)},
                {"frame": "step (graph.py:100)",
                 "samples": samples - int(samples * frame_share)},
            ],
        },
    }


def test_diff_profiles_names_dominant_kernel_and_frame():
    d = diff_profiles(_epoch(2.0, 0.2), _epoch(6.0, 0.7))
    assert d["dominant_kernel"]["family"] == "knn_search"
    assert d["dominant_kernel"]["delta_ms_per_dispatch"] == pytest.approx(4.0)
    assert d["dominant_kernel"]["ratio"] == pytest.approx(3.0)
    assert d["dominant_kernel"]["bound_by"] == "bandwidth"
    assert d["dominant_frame"]["frame"] == "search (knn.py:900)"
    assert d["dominant_frame"]["delta_share"] == pytest.approx(0.5)
    assert d["mfu_rolling_delta"] == 0.0


def test_diff_profiles_accepts_bench_artifacts():
    a = {"unit": "docs/s", "profile": [_epoch(1.0, 0.1), _epoch(2.0, 0.2)]}
    b = {"unit": "docs/s", "profile": [_epoch(3.0, 0.2)]}
    d = diff_profiles(a, b)  # last epoch of each artifact wins
    assert d["dominant_kernel"]["device_ms_per_dispatch_a"] == 2.0
    assert d["dominant_kernel"]["device_ms_per_dispatch_b"] == 3.0


def test_diff_profiles_rejects_profile_free_artifacts():
    with pytest.raises(ValueError, match="--profile"):
        diff_profiles({"unit": "docs/s"}, _epoch(1.0, 0.1))


def test_profile_epoch_embeds_host_and_families():
    prof = Profiler(sample_interval_ms=1e6)
    prof.record_dispatch("knn_search", 100.0, 1000.0, 1.0)
    with prof._lock:
        prof._stacks[("worker", ("f (a.py:1)", "g (a.py:2)"))] = 5
        prof.samples_total = 5
    ep = prof.profile_epoch()
    assert ep["families"]["knn_search"]["dispatches"] == 1
    frames = {e["frame"]: e["samples"] for e in ep["host"]["top_frames"]}
    assert frames == {"f (a.py:1)": 5, "g (a.py:2)": 5}
    # an epoch is diffable against itself (zero deltas)
    d = diff_profiles(ep, ep)
    assert d["dominant_kernel"]["delta_ms_per_dispatch"] == 0.0
