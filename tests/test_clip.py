"""CLIP dual encoder (models/clip.py) + multimodal embedder/index wiring
(BASELINE config 4: multimodal RAG with image+text embeddings)."""

from __future__ import annotations

import jax
import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.models import clip as clip_mod
from pathway_tpu.models.clip import (
    ClipConfig,
    clip_train_step,
    encode_image,
    encode_text,
    init_clip_params,
)


@pytest.fixture(autouse=True)
def _clear_graph():
    G.clear()
    yield
    G.clear()


N_CLASSES = 4


def _synthetic_pair(cls: int, config: ClipConfig, rng):
    """Image: a class-specific quadrant pattern (+noise); caption: a
    class-specific token bigram."""
    S = config.image_size
    px = rng.uniform(0, 0.15, (S, S, 3)).astype(np.float32)
    q = S // 2
    ys, xs = divmod(cls, 2)
    px[ys * q:(ys + 1) * q, xs * q:(xs + 1) * q] += 0.8
    ids = np.zeros((8,), np.int32)
    ids[0] = 10 + cls
    ids[1] = 100 + cls * 7
    mask = np.zeros((8,), bool)
    mask[:2] = True
    return px, ids, mask


_TRAINED: dict = {}


def _train_tiny(steps: int = 400):
    """Train once per test session (~60s on 1 CPU core) and reuse."""
    if "params" in _TRAINED:
        return (_TRAINED["config"], _TRAINED["params"], _TRAINED["loss"])
    from pathway_tpu.models.clip import make_clip_optimizer

    config = ClipConfig.tiny()
    params = init_clip_params(jax.random.PRNGKey(0), config)
    optimizer = make_clip_optimizer(1e-3)
    opt_state = optimizer.init(params)
    rng = np.random.default_rng(0)
    for _ in range(steps):
        pxs, idss, masks = zip(*[
            _synthetic_pair(c, config, rng) for c in range(N_CLASSES)])
        batch = {"pixels": np.stack(pxs), "ids": np.stack(idss),
                 "mask": np.stack(masks)}
        params, opt_state, loss = clip_train_step(
            params, opt_state, batch, config=config, optimizer=optimizer)
    _TRAINED.update(config=config, params=params, loss=float(loss))
    return config, params, float(loss)


def test_clip_shapes_and_normalization():
    config = ClipConfig.tiny()
    params = init_clip_params(jax.random.PRNGKey(1), config)
    rng = np.random.default_rng(1)
    px = rng.uniform(0, 1, (3, config.image_size, config.image_size, 3)
                     ).astype(np.float32)
    img = np.asarray(encode_image(params, px, config=config))
    assert img.shape == (3, config.embed_dim)
    np.testing.assert_allclose(np.linalg.norm(img, axis=1), 1.0, atol=1e-5)
    ids = rng.integers(1, 100, (3, 8)).astype(np.int32)
    mask = np.ones((3, 8), bool)
    txt = np.asarray(encode_text(params, ids, mask, config=config))
    assert txt.shape == (3, config.embed_dim)
    np.testing.assert_allclose(np.linalg.norm(txt, axis=1), 1.0, atol=1e-5)


def test_clip_contrastive_training_aligns_modalities():
    """After a short contrastive run, each caption's nearest image (in the
    shared space) is its own class — the property multimodal RAG needs."""
    config, params, loss = _train_tiny()
    assert loss < 0.5, f"contrastive loss did not drop: {loss}"
    rng = np.random.default_rng(7)
    pxs, idss, masks = zip(*[
        _synthetic_pair(c, config, rng) for c in range(N_CLASSES)])
    img = np.asarray(encode_image(params, np.stack(pxs), config=config))
    txt = np.asarray(encode_text(params, np.stack(idss), np.stack(masks),
                                 config=config))
    sim = txt @ img.T
    assert list(np.argmax(sim, axis=1)) == list(range(N_CLASSES))


def test_clip_embedder_joint_index_cross_modal():
    """Images indexed via ClipEmbedder.image(); text queries retrieve the
    right image through the shared space — one KNN index, two modalities."""
    from pathway_tpu.stdlib.indexing import default_brute_force_knn_document_index
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.xpacks.llm.embedders import ClipEmbedder

    config, params, _loss = _train_tiny()
    emb = ClipEmbedder(config=config, params=params)
    image_udf = emb.image()
    assert emb.get_embedding_dimension() == config.embed_dim
    assert image_udf.get_embedding_dimension() == config.embed_dim

    rng = np.random.default_rng(3)
    pairs = [_synthetic_pair(c, config, rng) for c in range(N_CLASSES)]
    schema = sch.schema_from_types(label=str, pixels=np.ndarray)
    images = pw.debug.table_from_rows(
        schema, [(f"class{c}", pairs[c][0]) for c in range(N_CLASSES)])
    images = images.select(images.label,
                           vec=image_udf(images.pixels))
    index = default_brute_force_knn_document_index(
        images.vec, images, dimensions=config.embed_dim)

    # queries are CAPTIONS embedded by the TEXT tower
    qvecs = emb.embed_text_batch  # not used via tokenizer: direct ids
    ids = np.stack([p[1] for p in pairs])
    mask = np.stack([p[2] for p in pairs])
    tvec = np.asarray(encode_text(params, ids, mask, config=config))
    qschema = sch.schema_from_types(cls=str, vec=np.ndarray)
    queries = pw.debug.table_from_rows(
        qschema, [(f"class{c}", tvec[c]) for c in range(N_CLASSES)])
    hits = index.query_as_of_now(queries.vec, number_of_matches=1)
    res = queries.select(
        q=queries.cls,
        hit=pw.apply(lambda t: t[0] if t else None,
                     hits.restrict(queries).label))
    rows = {r[0]: r[1] for r in
            pw.debug.table_to_pandas(res).itertuples(index=False)}
    assert rows == {f"class{c}": f"class{c}" for c in range(N_CLASSES)}


def test_load_image_decodes_png_bytes():
    import io

    from PIL import Image

    config = ClipConfig.tiny()
    arr = (np.arange(64 * 64 * 3).reshape(64, 64, 3) % 255).astype("uint8")
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    px = clip_mod.load_image(buf.getvalue(), config=config)
    assert px.shape == (config.image_size, config.image_size, 3)
    assert 0.0 <= px.min() and px.max() <= 1.0
