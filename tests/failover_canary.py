"""Write-path failover canary: automatic replica promotion with epoch
fencing, proven on a REAL multi-process fleet under closed-loop write
load — not mocks.

Drives ``bench._ReplicaFleet`` (engine/router.py + engine/replica.py +
engine/persistence.py) in write mode: every member carries a durable-ack
``/w`` route (a 200 means the row is fsynced in the primary root's WAL)
feeding an idempotent key->max aggregate, and the router classifies
``/w`` as a write path (primary-only, honest 503 + Retry-After during an
election). Three scenarios, each a hard gate:

1. **SIGKILL the primary under write load** — writer threads POST unique
   keys through the router front door, retrying until acked; the primary
   is SIGKILLed mid-stream. The router must elect the most-caught-up
   replica, the replica must promote (finish tailing, fence, truncate
   the torn tail, go read-write), and writes must resume. Gates: every
   ACKED write is present in the surviving root's WAL (zero acked-write
   loss), the recovered key->value aggregate is BYTE-IDENTICAL to an
   unkilled oracle run's, >= 1 promotion was observed, and the failover
   wall-clock is reported.
2. **SIGSTOP/SIGCONT split-brain** — the primary is frozen (sockets
   open, heartbeats silent): the staleness detector must declare it and
   promote the replica. The resumed zombie's next commit must refuse
   with ``FencedPrimaryError`` NAMING both epochs, and the root must
   still load as a single timeline.
3. **crash mid-promotion** — the elected candidate dies INSIDE the
   promotion (``replica.promote.crash`` fault, rc 3, after the epoch
   bump). The router must re-elect a survivor, which promotes with zero
   acked-write loss.

The scenarios' JSON is written as a CI artifact. Exits 0 iff all hold.
Run: ``python tests/failover_canary.py``.
Knobs: FAILOVER_WRITERS, FAILOVER_KEYS_PER_WRITER,
FAILOVER_ELECTION_MS, FAILOVER_BENCH_ARTIFACT (JSON path).
"""

from __future__ import annotations

import http.client
import json
import os
import pathlib
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

WRITERS = int(os.environ.get("FAILOVER_WRITERS", 4))
KEYS_PER_WRITER = int(os.environ.get("FAILOVER_KEYS_PER_WRITER", 30))
ELECTION_MS = int(os.environ.get("FAILOVER_ELECTION_MS", 1500))


def _post(port: int, path: str, doc: dict, timeout: float = 60.0):
    """One POST; returns (status, retry_after_or_None)."""
    body = json.dumps(doc).encode()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        return resp.status, resp.getheader("Retry-After")
    finally:
        conn.close()


def _write_until_acked(port: int, key: str, val: int,
                       deadline: float) -> None:
    """The client half of the durability contract: retry the SAME
    idempotent write until a 200 — the ack, not the request, is the
    moment the write exists. 503s carry an honest Retry-After (the
    election window); connection errors are a dying primary."""
    while time.monotonic() < deadline:
        try:
            status, retry_after = _post(port, "/w",
                                        {"wkey": key, "wval": val})
        except OSError:
            time.sleep(0.2)
            continue
        if status == 200:
            return
        time.sleep(min(float(retry_after or 1), 3.0))
    raise TimeoutError(f"write {key} never acked")


def _scan_write_aggregate(root: str) -> dict[str, int]:
    """Load the root's 'writes' WAL the way a hydrating replica would
    (same scanner: torn tails and fenced-zombie epoch regressions are
    truncated) and fold it into the program's key->max aggregate."""
    import pathway_tpu as pw
    from pathway_tpu.engine.persistence import PersistenceDriver

    driver = PersistenceDriver(
        pw.persistence.Config(
            backend=pw.persistence.Backend.filesystem(root)),
        read_only=True)
    agg: dict[str, int] = {}
    for rec in driver._log_for("writes").read_all():
        for entry in rec[1]:
            row, diff = entry[1], entry[2]
            if diff > 0:
                k, v = str(row[0]), int(row[1])
                agg[k] = max(agg.get(k, v), v)
    return agg


def _fleet(tmp: str):
    import bench

    return bench._ReplicaFleet(tmp, writes=True)


def scenario_sigkill_primary(out: dict) -> None:
    """SIGKILL under closed-loop write load; gate acked-write durability
    and aggregate byte-identity across the promotion."""
    tmp = tempfile.mkdtemp(prefix="failover_canary_")
    fleet = _fleet(tmp)
    acked: list[tuple[str, int]] = []
    lock = threading.Lock()
    try:
        fleet.start_router(write_paths=("/w",),
                           election_timeout_ms=ELECTION_MS)
        fleet.start_primary(register=True, snapshot_ticks=0)
        fleet.start_replica("r1")
        fleet.start_replica("r2")

        deadline = time.monotonic() + 300

        def writer(w: int):
            for j in range(KEYS_PER_WRITER):
                key, val = f"c{w}_k{j}", 1000 * w + j
                _write_until_acked(fleet.router.port, key, val, deadline)
                with lock:
                    acked.append((key, val))

        threads = [threading.Thread(target=writer, args=(w,), daemon=True)
                   for w in range(WRITERS)]
        for t in threads:
            t.start()
        # SIGKILL the primary once the stream is genuinely mid-flight
        total = WRITERS * KEYS_PER_WRITER
        while True:
            with lock:
                if len(acked) >= total // 4:
                    break
            time.sleep(0.02)
        fleet.procs["primary"].kill()
        killed_at = len(acked)
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), \
            "writers wedged — writes never resumed after failover"

        promoted = fleet.wait_promoted(1)
        assert fleet.router.promotions_total >= 1
        out["sigkill_promoted"] = promoted
        out["sigkill_acked_total"] = len(acked)
        out["sigkill_acked_before_kill"] = killed_at
        out["sigkill_failover_s"] = (
            None if fleet.router.failover_seconds is None
            else round(fleet.router.failover_seconds, 3))
        assert killed_at < len(acked), \
            "no write was acked AFTER the kill — failover untested"
    finally:
        fleet.stop()

    # durability gates, judged against the root itself (the processes
    # are gone — only the WAL can testify)
    recovered = _scan_write_aggregate(fleet.root)
    lost = [(k, v) for k, v in acked if recovered.get(k) != v]
    assert not lost, f"ACKED writes missing from the root: {lost[:10]}"
    # oracle: the same client workload against an unkilled primary —
    # the recovered aggregate must be byte-identical
    otmp = tempfile.mkdtemp(prefix="failover_oracle_")
    ofleet = _fleet(otmp)
    try:
        doc = ofleet.start_primary(snapshot_ticks=0)
        odeadline = time.monotonic() + 300
        for w in range(WRITERS):
            for j in range(KEYS_PER_WRITER):
                _write_until_acked(doc["port"], f"c{w}_k{j}",
                                   1000 * w + j, odeadline)
    finally:
        ofleet.stop()
    oracle = _scan_write_aggregate(ofleet.root)
    assert json.dumps(recovered, sort_keys=True) == \
        json.dumps(oracle, sort_keys=True), (
            "recovered aggregate diverged from the unkilled oracle: "
            f"only-recovered={sorted(set(recovered) - set(oracle))[:5]} "
            f"only-oracle={sorted(set(oracle) - set(recovered))[:5]}")
    out["sigkill_aggregate_keys"] = len(recovered)
    print(f"[gate1] {len(acked)} acked writes ({killed_at} pre-kill), "
          f"0 lost, aggregate byte-identical to oracle "
          f"({len(recovered)} keys), promoted={out['sigkill_promoted']}, "
          f"failover {out['sigkill_failover_s']}s")


def scenario_split_brain(out: dict) -> None:
    """SIGSTOP the primary; the staleness detector promotes the replica;
    the SIGCONTed zombie must self-fence BY NAME and the root must stay
    a single timeline."""
    tmp = tempfile.mkdtemp(prefix="failover_zombie_")
    fleet = _fleet(tmp)
    try:
        fleet.start_router(write_paths=("/w",),
                           election_timeout_ms=ELECTION_MS)
        fleet.start_primary(register=True, snapshot_ticks=0)
        fleet.start_replica("r1")
        deadline = time.monotonic() + 300
        _write_until_acked(fleet.router.port, "pre_stop", 1, deadline)
        fleet.sigstop("primary")
        promoted = fleet.wait_promoted(1)
        assert promoted == "r1", promoted
        out["zombie_failover_s"] = (
            None if fleet.router.failover_seconds is None
            else round(fleet.router.failover_seconds, 3))
        # the new primary accepts writes while the zombie is frozen
        _write_until_acked(fleet.router.port, "post_promote", 2, deadline)
        # wake the zombie: its next commit must refuse, naming epochs
        fleet.sigcont("primary")
        fence_deadline = time.monotonic() + 120
        stderr = ""
        while time.monotonic() < fence_deadline:
            stderr = fleet.stderr_text("primary")
            if "FencedPrimaryError" in stderr:
                break
            time.sleep(0.25)
        assert "FencedPrimaryError" in stderr, \
            f"zombie never self-fenced: {stderr[-800:]}"
        assert "holds fencing epoch 0" in stderr \
            and "root is at epoch 1" in stderr, (
                "fencing refusal must NAME both epochs: "
                f"{stderr[-800:]}")
    finally:
        fleet.stop()
    # single-timeline gate: the root still loads through the standard
    # scanner, and both acked writes survived the whole episode
    agg = _scan_write_aggregate(fleet.root)
    assert agg.get("pre_stop") == 1 and agg.get("post_promote") == 2, agg
    print(f"[gate2] zombie fenced by name (epoch 0 vs 1), root loads as "
          f"a single timeline, failover {out['zombie_failover_s']}s")


def scenario_crash_mid_promotion(out: dict) -> None:
    """The elected candidate dies inside the promotion (rc 3, post
    epoch-bump): the election must stay open and a later-arriving
    survivor must be elected and complete — zero acked writes lost."""
    tmp = tempfile.mkdtemp(prefix="failover_crash_")
    fleet = _fleet(tmp)
    try:
        fleet.start_router(write_paths=("/w",),
                           election_timeout_ms=ELECTION_MS)
        fleet.start_primary(register=True, snapshot_ticks=0)
        fleet.start_replica("r1", promote_crash=True)
        deadline = time.monotonic() + 300
        _write_until_acked(fleet.router.port, "survives", 7, deadline)
        fleet.procs["primary"].kill()
        # r1 is elected, bumps the epoch, then dies INSIDE the promotion
        crash_deadline = time.monotonic() + 120
        while time.monotonic() < crash_deadline:
            if fleet.procs["r1"].poll() is not None:
                break
            time.sleep(0.1)
        assert fleet.procs["r1"].poll() == 3, \
            f"candidate exit rc={fleet.procs['r1'].poll()}"
        assert fleet.router.promotions_total == 0
        # the survivor arrives late, catches up, and is elected
        fleet.start_replica("r2")
        promoted = fleet.wait_promoted(1)
        assert promoted == "r2", promoted
        _write_until_acked(fleet.router.port, "post_crash", 8, deadline)
    finally:
        fleet.stop()
    agg = _scan_write_aggregate(fleet.root)
    assert agg.get("survives") == 7 and agg.get("post_crash") == 8, agg
    out["crash_promoted"] = "r2"
    print("[gate3] crash-mid-promotion re-elected r2, zero acked writes "
          "lost across BOTH deaths")


def main() -> int:
    out: dict = {}
    scenario_sigkill_primary(out)
    scenario_split_brain(out)
    scenario_crash_mid_promotion(out)
    artifact = os.environ.get("FAILOVER_BENCH_ARTIFACT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump(out, f, indent=1)
    print(f"[failover-canary] all gates held: {json.dumps(out)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
