"""Runnable >>> examples on user-facing APIs (reference test strategy:
doctests run in CI, compute_and_print determinism makes them assertions —
SURVEY §4)."""

from __future__ import annotations

import doctest

import pytest

import pathway_tpu  # noqa: F401
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


def _run(module) -> None:
    results = doctest.testmod(module, verbose=False,
                              optionflags=doctest.NORMALIZE_WHITESPACE)
    assert results.failed == 0, f"{results.failed} doctest failures"
    assert results.attempted > 0, "no doctests found"


def test_table_doctests():
    from pathway_tpu.internals import table

    _run(table)


def test_debug_doctests():
    from pathway_tpu import debug

    _run(debug)


def test_reducers_doctests():
    from pathway_tpu.internals import reducers_frontend

    _run(reducers_frontend)


def test_sql_doctests():
    from pathway_tpu.internals import sql

    _run(sql)


def test_joins_doctests():
    from pathway_tpu.internals import joins

    _run(joins)


def test_temporal_doctests():
    from pathway_tpu.stdlib import temporal

    _run(temporal)
