"""Static pipeline analyzer (internals/static_check/): one true-positive
and one true-negative per diagnostic code, plus the three front doors —
``pw.static_check``, ``pw.run(static_check=...)`` and
``python -m pathway_tpu check``."""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import tempfile
import textwrap

import pytest

import pathway_tpu as pw
import pathway_tpu.internals.dtype as dt
import pathway_tpu.internals.schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.static_check import (CODES, Diagnostic, Severity,
                                                StaticCheckError, render)
from tests.utils import T


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


def codes(diags):
    return [d.code for d in diags]


def _ab_table():
    return T("""
    a | b
    1 | x
    """)


# ---------------------------------------------------------------------------
# PWT001 — binary operation on incompatible dtypes
# ---------------------------------------------------------------------------

def test_pwt001_int_plus_str_is_error():
    t = _ab_table()
    diags = pw.static_check(t.select(bad=t.a + t.b))
    assert codes(diags) == ["PWT001"]
    assert diags[0].is_error
    # the diagnostic points at the user's select line, not framework code
    assert diags[0].trace is not None
    assert diags[0].trace.file_name.endswith("test_static_check.py")


def test_pwt001_ordering_incomparable_dtypes():
    t = _ab_table()
    assert codes(pw.static_check(t.select(bad=t.a < t.b))) == ["PWT001"]


def test_pwt001_negative_valid_arithmetic():
    t = _ab_table()
    out = t.select(c=t.a * 2, d=t.b + t.b, e=t.a <= t.a)
    assert pw.static_check(out) == []


# ---------------------------------------------------------------------------
# PWT002 — impossible cast/convert
# ---------------------------------------------------------------------------

def test_pwt002_int_to_duration_cast_is_error():
    t = _ab_table()
    diags = pw.static_check(t.select(c=pw.cast(dt.DURATION, t.a)))
    assert codes(diags) == ["PWT002"]


def test_pwt002_negative_int_to_float_cast():
    t = _ab_table()
    assert pw.static_check(t.select(c=pw.cast(float, t.a))) == []


# ---------------------------------------------------------------------------
# PWT003 — join/groupby keys with incompatible dtypes
# ---------------------------------------------------------------------------

def test_pwt003_join_on_int_vs_str_key():
    left = T("""
    k | v
    1 | 2
    """)
    right = T("""
    k | w
    a | b
    """)
    joined = left.join(right, left.k == right.k).select(left.v, right.w)
    diags = pw.static_check(joined)
    assert "PWT003" in codes(diags)


def test_pwt003_negative_matching_key_dtypes():
    left = T("""
    k | v
    1 | 2
    """)
    right = T("""
    k | w
    1 | 3
    """)
    joined = left.join(right, left.k == right.k).select(left.v, right.w)
    assert pw.static_check(joined) == []


# ---------------------------------------------------------------------------
# PWT004 — dead dataflow
# ---------------------------------------------------------------------------

def test_pwt004_unreached_table_is_reported():
    t = _ab_table()
    live = t.select(c=t.a * 2)
    # computed, never consumed; the local ref keeps it alive in the weak
    # registry, exactly like a forgotten module-level table in a script
    dead = t.select(d=t.a + 1)  # noqa: F841
    diags = pw.static_check(live)
    assert codes(diags) == ["PWT004"]
    assert diags[0].severity is Severity.WARNING


def test_pwt004_negative_everything_reaches_the_sink():
    t = _ab_table()
    live = t.select(c=t.a * 2)
    assert pw.static_check(live) == []


def test_unreachable_table_errors_downgrade_to_dead_dataflow():
    # a defective table outside the outputs' upstream closure never runs:
    # it must warn as dead dataflow, not block a valid pipeline with errors
    t = _ab_table()
    live = t.select(c=t.a * 2)
    scratch = t.select(bad=t.a + t.b)  # noqa: F841 — int+str, kept alive
    diags = pw.static_check(live)
    assert codes(diags) == ["PWT004"]
    assert all(not d.is_error for d in diags)


# ---------------------------------------------------------------------------
# PWT005 — streaming source never reaches a sink
# ---------------------------------------------------------------------------

def _streaming_source(tmp_dir):
    return pw.io.fs.read(tmp_dir, format="json", mode="streaming",
                         schema=sch.schema_from_types(a=int))


def test_pwt005_streaming_source_without_output_binder(tmp_path):
    source = _streaming_source(str(tmp_path))  # noqa: F841 — keep alive
    diags = pw.static_check()
    assert codes(diags) == ["PWT005"]


def test_pwt005_negative_subscribed_source(tmp_path):
    t = _streaming_source(str(tmp_path))
    pw.io.subscribe(t, lambda *a, **k: None)
    assert pw.static_check() == []


def test_pwt005_negative_static_mode_source(tmp_path):
    # a static read terminates on its own — no "runs forever" diagnostic
    source = pw.io.fs.read(  # noqa: F841 — keep alive
        str(tmp_path), format="json", mode="static",
        schema=sch.schema_from_types(a=int))
    assert codes(pw.static_check()) == []


# ---------------------------------------------------------------------------
# PWT006 — non-deterministic / async UDF in a persisted pipeline
# ---------------------------------------------------------------------------

def test_pwt006_nondeterministic_udf_with_persistence():
    t = _ab_table()
    inc = pw.udf(lambda x: x + 1)  # deterministic defaults to False
    out = t.select(c=inc(t.a))
    diags = pw.static_check(out, persistence=True)
    assert codes(diags) == ["PWT006"]


def test_pwt006_negative_deterministic_udf_or_no_persistence():
    t = _ab_table()
    inc_det = pw.udf(lambda x: x + 1, deterministic=True)
    assert pw.static_check(t.select(c=inc_det(t.a)), persistence=True) == []
    G.clear()
    t = _ab_table()
    inc = pw.udf(lambda x: x + 1)
    assert pw.static_check(t.select(c=inc(t.a)), persistence=False) == []


# ---------------------------------------------------------------------------
# PWT007 — universe mismatch the solver would reject
# ---------------------------------------------------------------------------

def test_pwt007_update_cells_on_disjoint_universes():
    a = T("""
    x
    1
    """)
    b = T("""
    x
    2
    """)
    disjoint = a.promise_universes_are_disjoint(b)
    diags = pw.static_check(disjoint.update_cells(b))
    assert "PWT007" in codes(diags)
    pwt007 = [d for d in diags if d.code == "PWT007"]
    assert pwt007[0].is_error


def test_pwt007_unproven_subset_is_info_not_error():
    a = T("""
    x
    1
    """)
    b = T("""
    x
    2
    """)
    diags = pw.static_check(a.update_cells(b))
    assert codes(diags) == ["PWT007"]
    assert diags[0].severity is Severity.INFO


def test_pwt007_negative_proven_equal_universes():
    t = _ab_table()
    reshaped = t.select(c=t.a).with_universe_of(t)
    assert pw.static_check(reshaped) == []


# ---------------------------------------------------------------------------
# PWT008 — get() default silently widens the element dtype
# ---------------------------------------------------------------------------

def test_pwt008_str_default_on_int_tuple():
    t = _ab_table()
    tup = t.select(tu=pw.make_tuple(t.a, t.a))
    got = tup.select(g=tup.tu.get(0, default="missing"))
    diags = pw.static_check(got)
    assert codes(diags) == ["PWT008"]


def test_pwt008_negative_default_matches_element_dtype():
    t = _ab_table()
    tup = t.select(tu=pw.make_tuple(t.a, t.a))
    got = tup.select(g=tup.tu.get(0, default=7))
    assert pw.static_check(got) == []


# ---------------------------------------------------------------------------
# PWT009 — sink format cannot carry the bound table's schema
# ---------------------------------------------------------------------------

def test_pwt009_tuple_column_into_csv_sink(tmp_path):
    t = _ab_table()
    tup = t.select(tu=pw.make_tuple(t.a, t.a))
    pw.io.fs.write(tup, str(tmp_path / "out.csv"), format="csv")
    diags = pw.static_check()
    assert codes(diags) == ["PWT009"]


def test_pwt009_negative_scalar_columns_into_csv(tmp_path):
    t = _ab_table()
    pw.io.fs.write(t.select(c=t.a * 2), str(tmp_path / "out.csv"),
                   format="csv")
    assert pw.static_check() == []


# ---------------------------------------------------------------------------
# PWT010 — redundant cast
# ---------------------------------------------------------------------------

def test_pwt010_cast_to_same_dtype_is_info():
    t = _ab_table()
    diags = pw.static_check(t.select(c=pw.cast(int, t.a)))
    assert codes(diags) == ["PWT010"]
    assert diags[0].severity is Severity.INFO


def test_pwt010_negative_widening_cast():
    t = _ab_table()
    assert pw.static_check(t.select(c=pw.cast(float, t.a))) == []


# ---------------------------------------------------------------------------
# PWT011 — ix key is not a pointer
# ---------------------------------------------------------------------------

def test_pwt011_ix_with_int_key():
    t = _ab_table()
    diags = pw.static_check(t.ix(t.a))
    assert codes(diags) == ["PWT011"]


def test_pwt011_negative_ix_with_id_pointer():
    t = _ab_table()
    assert pw.static_check(t.ix(t.id)) == []


# ---------------------------------------------------------------------------
# PWT012 — no retries AND no escalation: a crash silently drops the source
# ---------------------------------------------------------------------------

def _no_retry_source(tmp_dir):
    t = pw.io.fs.read(tmp_dir, format="json", mode="streaming",
                      schema=sch.schema_from_types(a=int),
                      connector_policy=pw.ConnectorPolicy(max_retries=0))
    pw.io.subscribe(t, lambda *a, **k: None)
    return t


def test_pwt012_no_retries_without_escalation_warns(tmp_path):
    _no_retry_source(str(tmp_path))
    diags = pw.static_check(terminate_on_error=False)
    assert codes(diags) == ["PWT012"]
    assert not diags[0].is_error


def test_pwt012_negative_terminate_on_error_true(tmp_path):
    # escalation covers the crash: pw.run would re-raise it
    _no_retry_source(str(tmp_path))
    assert pw.static_check(terminate_on_error=True) == []


def test_pwt012_negative_retries_available(tmp_path):
    t = pw.io.fs.read(str(tmp_path), format="json", mode="streaming",
                      schema=sch.schema_from_types(a=int),
                      connector_policy=pw.ConnectorPolicy(max_retries=3))
    pw.io.subscribe(t, lambda *a, **k: None)
    assert pw.static_check(terminate_on_error=False) == []


def test_pwt012_run_wide_default_policy(tmp_path):
    # the hazard also arises from pw.run(connector_policy=...) applying a
    # zero-retry default to sources that set no policy of their own
    t = pw.io.fs.read(str(tmp_path), format="json", mode="streaming",
                      schema=sch.schema_from_types(a=int))
    pw.io.subscribe(t, lambda *a, **k: None)
    diags = pw.static_check(
        terminate_on_error=False,
        connector_policy=pw.ConnectorPolicy(max_retries=0))
    assert codes(diags) == ["PWT012"]
    # a per-source policy with retries overrides the risky default
    G.clear()
    t2 = pw.io.fs.read(str(tmp_path), format="json", mode="streaming",
                       schema=sch.schema_from_types(a=int),
                       connector_policy=pw.ConnectorPolicy(max_retries=2))
    pw.io.subscribe(t2, lambda *a, **k: None)
    assert pw.static_check(
        terminate_on_error=False,
        connector_policy=pw.ConnectorPolicy(max_retries=0)) == []


def test_pwt012_negative_unknown_run_mode(tmp_path):
    # the CLI path does not know terminate_on_error — no guessing
    _no_retry_source(str(tmp_path))
    assert pw.static_check() == []


def test_pwt012_surfaces_through_pw_run(tmp_path, caplog):
    _no_retry_source(str(tmp_path))
    from pathway_tpu.internals.run import _run_static_check

    with caplog.at_level(logging.WARNING, "pathway_tpu.static_check"):
        _run_static_check("warn", None, False)
    assert any("PWT012" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# diagnostics plumbing
# ---------------------------------------------------------------------------

def test_every_code_has_registered_severity_and_summary():
    assert set(CODES) >= {f"PWT{i:03d}" for i in range(13)}
    for code, (severity, summary) in CODES.items():
        assert isinstance(severity, Severity)
        assert summary


def test_unknown_code_is_rejected():
    with pytest.raises(ValueError, match="unknown diagnostic code"):
        Diagnostic(code="PWT999", message="nope")


def test_deep_linear_pipeline_does_not_hit_recursion_limit():
    # the analyzer's DAG walk must be iterative: thousands of chained
    # selects are a legal pipeline, not a stack overflow
    t = T("""
    a
    1
    """)
    for _ in range(1200):
        t = t.select(a=pw.this.a)
    assert pw.static_check(t) == []


def test_render_orders_errors_first():
    out = render([
        Diagnostic(code="PWT010", message="an info"),
        Diagnostic(code="PWT001", message="an error"),
        Diagnostic(code="PWT004", message="a warning"),
    ])
    assert out.index("PWT001") < out.index("PWT004") < out.index("PWT010")


# ---------------------------------------------------------------------------
# pw.run(static_check=...) gate
# ---------------------------------------------------------------------------

def test_run_static_check_error_raises_before_execution():
    t = _ab_table()
    bad = t.select(c=t.a + t.b)
    pw.io.subscribe(bad, lambda *a, **k: None)
    with pytest.raises(StaticCheckError) as exc_info:
        pw.run(static_check="error")
    assert any(d.code == "PWT001" for d in exc_info.value.diagnostics)


def test_run_static_check_warn_logs_and_still_runs(caplog):
    t = _ab_table()
    seen = []
    pw.io.subscribe(t.select(c=t.a * 2), lambda *a, **k: seen.append(a))
    dead = t.select(dead=t.a + 1)  # noqa: F841 — keep alive
    with caplog.at_level("WARNING", logger="pathway_tpu.static_check"):
        pw.run(static_check="warn")
    assert any("PWT004" in r.message for r in caplog.records)
    assert seen  # the pipeline still executed


def test_run_static_check_rejects_unknown_mode():
    with pytest.raises(ValueError, match="static_check must be"):
        pw.run(static_check="loudly")


def test_run_static_check_info_diagnostics_log_at_info(caplog):
    # a redundant cast is informational — it must not surface as a
    # WARNING record that log-level alerting would page on
    t = _ab_table()
    seen = []
    pw.io.subscribe(t.select(c=pw.cast(int, t.a)),
                    lambda *a, **k: seen.append(a))
    with caplog.at_level("INFO", logger="pathway_tpu.static_check"):
        pw.run(static_check="warn")
    recs = [r for r in caplog.records if "PWT010" in r.message]
    assert recs, caplog.records
    assert all(r.levelno == logging.INFO for r in recs)
    assert seen  # the pipeline still executed


# ---------------------------------------------------------------------------
# CLI: python -m pathway_tpu check
# ---------------------------------------------------------------------------

def _run_check(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu", "check", *args],
        capture_output=True, text=True, env=env, timeout=300)


def test_cli_check_exits_nonzero_on_seeded_dtype_error(tmp_path):
    script = tmp_path / "bad_pipeline.py"
    script.write_text(textwrap.dedent("""
        import pathway_tpu as pw
        t = pw.debug.table_from_markdown('''
        a | b
        1 | x
        ''')
        out = t.select(c=t.a + t.b)
        pw.debug.compute_and_print(out)
    """))
    proc = _run_check(str(script))
    assert proc.returncode == 1, proc.stderr
    assert "PWT001" in proc.stdout
    # the seeded pipeline must not have actually executed
    assert "Error" not in proc.stdout.splitlines()[0]


def test_cli_check_reports_import_failure_as_pwt000(tmp_path):
    script = tmp_path / "broken.py"
    script.write_text("raise RuntimeError('boom at import time')\n")
    proc = _run_check(str(script))
    assert proc.returncode == 1
    assert "PWT000" in proc.stdout


def test_cli_check_passes_on_clean_script(tmp_path):
    script = tmp_path / "clean_pipeline.py"
    script.write_text(textwrap.dedent("""
        import pathway_tpu as pw
        t = pw.debug.table_from_markdown('''
        a
        1
        ''')
        pw.debug.compute_and_print(t.select(c=t.a * 2))
    """))
    proc = _run_check(str(script))
    assert proc.returncode == 0, proc.stdout + proc.stderr


GUARDED = """
import pathway_tpu as pw

def main():
    t = pw.debug.table_from_markdown('''
    a
    1
    ''')
    pw.debug.compute_and_print(t.select(c=t.a * 2))

if __name__ == "__main__":
    main()
"""


def test_cli_check_reports_empty_collection_distinctly(tmp_path):
    # a graph hidden behind __main__ must not read as "clean": without
    # --require-pipeline it passes but says so; with the flag it fails
    script = tmp_path / "guarded.py"
    script.write_text(GUARDED)
    proc = _run_check(str(script))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no pipeline collected" in proc.stderr
    proc = _run_check("--require-pipeline", str(script))
    assert proc.returncode == 1
    assert "no pipeline collected" in proc.stderr


def test_cli_check_pathway_check_hook_is_analyzed(tmp_path):
    # the __pathway_check__ convention (used by examples/) feeds the
    # analyzer a real graph — including its errors
    script = tmp_path / "hooked.py"
    script.write_text(GUARDED + """
elif __name__ == "__pathway_check__":
    t = pw.debug.table_from_markdown('''
    a | b
    1 | x
    ''')
    pw.debug.compute_and_print(t.select(c=t.a + t.b))
""")
    proc = _run_check("--require-pipeline", str(script))
    assert proc.returncode == 1
    assert "PWT001" in proc.stdout


def test_cli_check_nonzero_system_exit_is_pwt000(tmp_path):
    script = tmp_path / "exits.py"
    script.write_text("import sys\nsys.exit(3)\n")
    proc = _run_check(str(script))
    assert proc.returncode == 1
    assert "PWT000" in proc.stdout and "status 3" in proc.stdout


def test_cli_check_clean_system_exit_is_ok(tmp_path):
    script = tmp_path / "clean_exit.py"
    script.write_text(textwrap.dedent("""
        import sys
        import pathway_tpu as pw
        t = pw.debug.table_from_markdown('''
        a
        1
        ''')
        pw.debug.compute_and_print(t.select(c=t.a * 2))
        sys.exit(0)
    """))
    proc = _run_check("--require-pipeline", str(script))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_check_clean_exit_still_analyzes_unbound_tables(tmp_path):
    # sys.exit(0) drops the module globals; the registry holds tables only
    # weakly, so without pinning the seeded error would vanish un-reported
    script = tmp_path / "exit_with_bad_table.py"
    script.write_text(textwrap.dedent("""
        import sys
        import pathway_tpu as pw
        t = pw.debug.table_from_markdown('''
        a | b
        1 | x
        ''')
        bad = t.select(c=t.a + t.b)
        sys.exit(0)
    """))
    proc = _run_check(str(script))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "PWT001" in proc.stdout


def test_cli_check_directory_skips_helper_modules(tmp_path):
    # only pipeline entry points gate a directory: _*.py and __init__.py
    # must be neither imported nor failed under --require-pipeline
    (tmp_path / "pipeline.py").write_text(textwrap.dedent("""
        import pathway_tpu as pw
        t = pw.debug.table_from_markdown('''
        a
        1
        ''')
        pw.debug.compute_and_print(t.select(c=t.a * 2))
    """))
    (tmp_path / "_helpers.py").write_text("CONSTANT = 1\n")
    (tmp_path / "__init__.py").write_text("")
    (tmp_path / ".hidden").mkdir()
    (tmp_path / ".hidden" / "junk.py").write_text("raise RuntimeError\n")
    proc = _run_check("--require-pipeline", str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "_helpers" not in proc.stderr and "junk" not in proc.stderr


def test_cli_check_scripts_share_helper_with_cold_import_cache(tmp_path):
    # two scripts importing the same graph-building helper must each
    # collect it: the import cache is reset between scripts, otherwise
    # the second one would see a cached (already-executed) module and
    # fail the gate with "no pipeline collected"
    (tmp_path / "_shared.py").write_text(textwrap.dedent("""
        import pathway_tpu as pw
        t = pw.debug.table_from_markdown('''
        a
        1
        ''')
        pw.debug.compute_and_print(t.select(c=t.a * 2))
    """))
    for name in ("first.py", "second.py"):
        (tmp_path / name).write_text("import _shared\n")
    proc = _run_check("--require-pipeline", str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
