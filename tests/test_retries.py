"""Shared retry strategies (internals/retries.py): the one delay-schedule
implementation behind async UDF retries (internals/udfs.py) and connector
supervision (engine/supervisor.py)."""

from __future__ import annotations

import asyncio

import pytest

from pathway_tpu.internals.retries import (ExponentialBackoffRetryStrategy,
                                           FixedDelayRetryStrategy,
                                           NoRetryStrategy)


def _seq(strategy, n):
    return [strategy.delay_for_attempt(i) for i in range(n)]


def test_fixed_delay_sequence_is_constant():
    s = FixedDelayRetryStrategy(max_retries=5, delay_ms=250)
    assert _seq(s, 4) == [0.25, 0.25, 0.25, 0.25]


def test_exponential_sequence_without_jitter():
    s = ExponentialBackoffRetryStrategy(initial_delay_ms=100,
                                        backoff_factor=2.0)
    assert _seq(s, 4) == [0.1, 0.2, 0.4, 0.8]


def test_exponential_max_delay_caps_the_schedule():
    s = ExponentialBackoffRetryStrategy(initial_delay_ms=100,
                                        backoff_factor=10.0,
                                        max_delay_ms=500)
    assert _seq(s, 4) == [0.1, 0.5, 0.5, 0.5]


def test_exponential_full_jitter_is_seeded_and_bounded():
    mk = lambda: ExponentialBackoffRetryStrategy(  # noqa: E731
        initial_delay_ms=100, backoff_factor=2.0, max_delay_ms=300,
        jitter=True, seed=7)
    a, b = _seq(mk(), 6), _seq(mk(), 6)
    assert a == b  # same seed → identical schedule (deterministic tests)
    # full jitter: uniform over [0, capped_delay]
    caps = [0.1, 0.2, 0.3, 0.3, 0.3, 0.3]
    assert all(0.0 <= d <= cap for d, cap in zip(a, caps))
    # a different seed draws a different schedule
    other = ExponentialBackoffRetryStrategy(
        initial_delay_ms=100, backoff_factor=2.0, max_delay_ms=300,
        jitter=True, seed=8)
    assert _seq(other, 6) != a


def test_async_invoke_retries_then_succeeds(monkeypatch):
    sleeps: list[float] = []

    async def fake_sleep(d):
        sleeps.append(d)

    monkeypatch.setattr(asyncio, "sleep", fake_sleep)
    attempts = []

    async def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise ValueError("transient")
        return "ok"

    s = ExponentialBackoffRetryStrategy(max_retries=3, initial_delay_ms=100,
                                        backoff_factor=2.0)
    assert asyncio.run(s.invoke(flaky)) == "ok"
    assert len(attempts) == 3
    assert sleeps == [0.1, 0.2]  # invoke sleeps the declared schedule


def test_async_invoke_exhausts_and_reraises(monkeypatch):
    async def fake_sleep(d):
        pass

    monkeypatch.setattr(asyncio, "sleep", fake_sleep)
    attempts = []

    async def always_fails():
        attempts.append(1)
        raise ValueError("permanent")

    s = FixedDelayRetryStrategy(max_retries=2, delay_ms=1)
    with pytest.raises(ValueError, match="permanent"):
        asyncio.run(s.invoke(always_fails))
    assert len(attempts) == 3  # initial + 2 retries


def test_no_retry_strategy_has_no_schedule():
    with pytest.raises(RuntimeError):
        NoRetryStrategy().delay_for_attempt(0)


def test_udfs_module_reexports_shared_implementation():
    """The historical import home keeps working and IS the shared class —
    one schedule for UDF retries and connector restarts."""
    from pathway_tpu.internals import retries, udfs

    assert udfs.ExponentialBackoffRetryStrategy \
        is retries.ExponentialBackoffRetryStrategy
    assert udfs.FixedDelayRetryStrategy is retries.FixedDelayRetryStrategy
    assert udfs.NoRetryStrategy is retries.NoRetryStrategy
    assert udfs.AsyncRetryStrategy is retries.AsyncRetryStrategy


def test_connector_policy_normalizes_no_retry():
    import pathway_tpu as pw

    p = pw.ConnectorPolicy(max_retries=5, retry_strategy=NoRetryStrategy())
    assert p.max_retries == 0
