"""window_join: tumbling/sliding/session × inner/left/right/outer, with
retractions, verified against a brute-force model, at n_workers ∈ {1, 8}
(reference: python/pathway/stdlib/temporal/_window_join.py)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.delta import row_fingerprint
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner
from tests.utils import T, rows_of


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


LEFT_MD = """
k | t  | a  | _time | _diff
x | 1  | 10 | 2     | 1
x | 4  | 11 | 2     | 1
y | 3  | 12 | 2     | 1
x | 7  | 13 | 4     | 1
x | 4  | 11 | 6     | -1
z | 2  | 14 | 6     | 1
"""

RIGHT_MD = """
k | t  | b  | _time | _diff
x | 2  | 20 | 2     | 1
x | 5  | 21 | 2     | 1
y | 9  | 22 | 4     | 1
x | 6  | 23 | 6     | 1
w | 1  | 24 | 6     | 1
"""

# final states after the update stream above settles
LEFT_ROWS = [("x", 1, 10), ("y", 3, 12), ("x", 7, 13), ("z", 2, 14)]
RIGHT_ROWS = [("x", 2, 20), ("x", 5, 21), ("y", 9, 22), ("x", 6, 23),
              ("w", 1, 24)]


def _tumbling_wins(t, dur):
    s = (t // dur) * dur
    return [(s, s + dur)]


def _sliding_wins(t, hop, dur):
    out = []
    i = (t - dur) // hop + 1
    while True:
        s = i * hop
        if s > t:
            break
        if t < s + dur:
            out.append((s, s + dur))
        i += 1
    return out


def _session_spans(times, max_gap):
    spans = {}
    ts = sorted(set(times))
    if not ts:
        return spans
    cur = [ts[0]]
    for t in ts[1:]:
        if t - cur[-1] < max_gap:
            cur.append(t)
        else:
            for m in cur:
                spans[m] = (cur[0], cur[-1])
            cur = [t]
    for m in cur:
        spans[m] = (cur[0], cur[-1])
    return spans


def _model(how, wins_of=None, session_gap=None):
    """Brute-force expected multiset of (a, b) pairs."""
    out = []
    if session_gap is not None:
        keys = {k for k, _, _ in LEFT_ROWS} | {k for k, _, _ in RIGHT_ROWS}
        for k in keys:
            lts = [t for kk, t, _ in LEFT_ROWS if kk == k]
            rts = [t for kk, t, _ in RIGHT_ROWS if kk == k]
            spans = _session_spans(lts + rts, session_gap)
            sess = sorted({spans[t] for t in lts + rts})
            for sp in sess:
                lg = [(a,) for kk, t, a in LEFT_ROWS
                      if kk == k and spans[t] == sp]
                rg = [(b,) for kk, t, b in RIGHT_ROWS
                      if kk == k and spans[t] == sp]
                out.extend(_join_groups(lg, rg, how))
        return sorted(out, key=repr)
    pairs = {}
    for k, t, a in LEFT_ROWS:
        for w in wins_of(t):
            pairs.setdefault((k, w), [[], []])[0].append((a,))
    for k, t, b in RIGHT_ROWS:
        for w in wins_of(t):
            pairs.setdefault((k, w), [[], []])[1].append((b,))
    for lg, rg in pairs.values():
        out.extend(_join_groups(lg, rg, how))
    return sorted(out, key=repr)


def _join_groups(lg, rg, how):
    out = []
    if lg and rg:
        for (a,) in lg:
            for (b,) in rg:
                out.append((a, b))
    if how in ("left", "outer") and lg and not rg:
        out.extend((a, None) for (a,) in lg)
    if how in ("right", "outer") and rg and not lg:
        out.extend((None, b) for (b,) in rg)
    return out


def _run(window, how, n_workers):
    G.clear()
    left = T(LEFT_MD)
    right = T(RIGHT_MD)
    res = pw.temporal.window_join(
        left, right, left.t, right.t, window, left.k == right.k,
        how=how).select(a=pw.left.a, b=pw.right.b)
    runner = GraphRunner()
    cap = runner.capture(res)
    runner.run_batch(n_workers=n_workers)
    rows = sorted((tuple(r) for r in cap.snapshot().values()), key=repr)
    stream = sorted((k, row_fingerprint(r), t, d)
                    for k, r, t, d in cap.consolidated_events())
    G.clear()
    return rows, stream


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_tumbling_window_join(how):
    rows, _ = _run(pw.temporal.tumbling(duration=3), how, 1)
    assert rows == _model(how, wins_of=lambda t: _tumbling_wins(t, 3))


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_sliding_window_join(how):
    rows, _ = _run(pw.temporal.sliding(hop=2, duration=4), how, 1)
    assert rows == _model(how, wins_of=lambda t: _sliding_wins(t, 2, 4))


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_session_window_join(how):
    rows, _ = _run(pw.temporal.session(max_gap=2), how, 1)
    assert rows == _model(how, session_gap=2)


@pytest.mark.parametrize("window", [
    pw.temporal.tumbling(duration=3),
    pw.temporal.sliding(hop=2, duration=4),
    pw.temporal.session(max_gap=2),
], ids=["tumbling", "sliding", "session"])
@pytest.mark.parametrize("how", ["inner", "outer"])
def test_window_join_sharded_identical(window, how):
    """Full update stream (incl. retraction) must be byte-identical at
    n_workers ∈ {1, 8}."""
    rows1, stream1 = _run(window, how, 1)
    rows8, stream8 = _run(window, how, 8)
    assert rows1 == rows8
    assert stream1 == stream8


def test_session_join_predicate_mode():
    rows, _ = _run(pw.temporal.session(
        predicate=lambda a, b: b - a < 2), "inner", 1)
    assert rows == _model("inner", session_gap=2)


def test_window_join_result_composes():
    """select() returns a plain Table that composes with filter/groupby."""
    left = T(LEFT_MD)
    right = T(RIGHT_MD)
    res = pw.temporal.window_join(
        left, right, left.t, right.t, pw.temporal.tumbling(duration=3),
        left.k == right.k, how="inner").select(
        k=pw.left.k, a=pw.left.a, b=pw.right.b)
    agg = res.groupby(res.k).reduce(res.k, n=pw.reducers.count())
    big = agg.filter(agg.n > 1)
    runner = GraphRunner()
    cap = runner.capture(big)
    runner.run_batch()
    got = dict((r[0], r[1]) for r in cap.snapshot().values())
    model = {}
    for a, b in _model("inner", wins_of=lambda t: _tumbling_wins(t, 3)):
        k = next(kk for kk, _, aa in LEFT_ROWS if aa == a)
        model[k] = model.get(k, 0) + 1
    model = {k: v for k, v in model.items() if v > 1}
    assert got == model
