"""Sharded multi-worker dataflow execution (engine/graph.py Scheduler with
n_workers > 1): key-routed exchange at stateful operators, per-worker
source partitioning (reference: src/engine/dataflow/shard.rs — shard =
key & mask; exchange on arrange/join/group, dataflow.rs:2276,2904;
per-worker source reads, src/connectors/mod.rs:400).

The contract under test: results are byte-identical for n_workers ∈ {1, 8}
AND the work is actually partitioned (several workers hold disjoint
operator state)."""

from __future__ import annotations

import os

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.delta import row_fingerprint
from pathway_tpu.engine.operators import (ColumnarGroupByOperator,
                                          JoinOperator)
from pathway_tpu.internals.runner import GraphRunner
from tests.utils import T

N_WORKERS = 8


def _run_n(tables, n_workers):
    runner = GraphRunner()
    caps = [runner.capture(t) for t in tables]
    runner.run_batch(n_workers=n_workers)
    return caps, runner


def _stream(cap):
    return sorted((k, row_fingerprint(r), t, d)
                  for k, r, t, d in cap.consolidated_events())


def _snap(cap):
    return {k: row_fingerprint(r) for k, r in cap.snapshot().items()}


def _pipeline():
    """groupby + join + filter over an update stream with retractions."""
    sales = T("""
    shop | item | qty | _time | _diff
    s0   | a    | 3   | 2     | 1
    s1   | a    | 1   | 2     | 1
    s2   | b    | 2   | 2     | 1
    s3   | b    | 5   | 4     | 1
    s4   | c    | 7   | 4     | 1
    s0   | a    | 3   | 6     | -1
    s5   | a    | 9   | 6     | 1
    s6   | d    | 2   | 6     | 1
    s7   | c    | 1   | 8     | 1
    """)
    info = T("""
    item | price
    a    | 10
    b    | 20
    c    | 30
    d    | 40
    e    | 50
    """)
    totals = sales.groupby(sales.item).reduce(
        sales.item,
        total_qty=pw.reducers.sum(sales.qty),
        n=pw.reducers.count(),
    )
    joined = totals.join(info, totals.item == info.item).select(
        totals.item, totals.total_qty, info.price,
        revenue=totals.total_qty * info.price,
    )
    big = joined.filter(joined.revenue >= 60)
    return sales, totals, joined, big


def test_groupby_join_identical_across_workers():
    caps1, _ = _run_n(list(_pipeline()), 1)
    capsN, _ = _run_n(list(_pipeline()), N_WORKERS)
    for c1, cN in zip(caps1, capsN):
        assert _stream(c1) == _stream(cN)
        assert _snap(c1) == _snap(cN)


def test_work_is_actually_partitioned():
    # enough distinct keys/groups that >1 of 8 workers must own state
    rows = "\n".join(f"u{i} | g{i % 16} | {i}" for i in range(64))
    t = T("user | grp | x\n" + rows)
    totals = t.groupby(t.grp).reduce(t.grp, s=pw.reducers.sum(t.x))
    joined = totals.join(t, totals.grp == t.grp).select(
        t.user, totals.s)
    _, runner = _run_n([joined], N_WORKERS)
    sched = runner._scheduler
    assert sched.n_workers == N_WORKERS

    def replicas_of(op_type):
        for node in runner.graph.nodes:
            if isinstance(node.op, op_type):
                return sched._replicas[node.id]
        raise AssertionError(f"no {op_type.__name__} node")

    greps = replicas_of(ColumnarGroupByOperator)
    assert len(greps) == N_WORKERS

    def live_groups(rep):
        return [gk for gk, code in rep._by_gkey.items()
                if rep._cnt[code] > 0]

    occupied = [rep for rep in greps if live_groups(rep)]
    assert len(occupied) >= 2, "groupby state not partitioned"
    all_groups = [g for rep in greps for g in live_groups(rep)]
    assert len(all_groups) == len(set(all_groups)) == 16, "shards overlap"

    jreps = replicas_of(JoinOperator)
    occupied_j = [rep for rep in jreps if rep.left or rep.right]
    assert len(occupied_j) >= 2, "join state not partitioned"


def test_source_rows_partitioned_across_workers():
    rows = "\n".join(f"k{i} | {i}" for i in range(32))
    t = T("k | x\n" + rows)
    out = t.select(t.k, y=t.x + 1)
    caps, runner = _run_n([out], N_WORKERS)
    assert len(caps[0].events) == 32
    sched = runner._scheduler
    src = next(n for n in runner.graph.nodes
               if type(n.op).__name__ == "SourceOperator")
    assert len(sched._replicas[src.id]) == N_WORKERS


def test_outer_join_with_nulls_sharded():
    left = T("""
    k  | v
    a  | 1
    b  | 2
    c  |
    """)
    right = T("""
    k  | w
    b  | 20
    d  | 40
    """)
    j = left.join_outer(right, left.k == right.k).select(
        lk=left.k, rk=right.k, v=left.v, w=right.w)
    caps1, _ = _run_n([j], 1)
    capsN, _ = _run_n([j], N_WORKERS)
    assert _stream(caps1[0]) == _stream(capsN[0])


def test_windowed_aggregation_sharded():
    t = T("""
    sensor | v | at | _time
    a      | 1 | 0  | 2
    b      | 2 | 1  | 2
    a      | 3 | 4  | 4
    b      | 4 | 5  | 4
    a      | 5 | 9  | 6
    b      | 6 | 12 | 8
    """)
    win = pw.temporal.windowby(
        t, t.at, window=pw.temporal.tumbling(4), instance=t.sensor,
    ).reduce(
        sensor=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    caps1, _ = _run_n([win], 1)
    capsN, _ = _run_n([win], N_WORKERS)
    assert _snap(caps1[0]) == _snap(capsN[0])


def test_windowby_delay_behavior_sharded():
    # buffered release rides a global watermark shared across workers: the
    # per-tick emission stream (not just the final state) must match n=1
    t = T("""
    sensor | v | at | _time
    a      | 1 | 0  | 2
    b      | 2 | 1  | 2
    a      | 3 | 6  | 4
    b      | 4 | 7  | 4
    a      | 5 | 13 | 6
    """)
    win = pw.temporal.windowby(
        t, t.at, window=pw.temporal.tumbling(4), instance=t.sensor,
        behavior=pw.temporal.common_behavior(delay=4),
    ).reduce(
        sensor=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    caps1, _ = _run_n([win], 1)
    capsN, _ = _run_n([win], N_WORKERS)
    assert _stream(caps1[0]) == _stream(capsN[0])


def test_iterate_gathers_and_matches():
    edges = T("""
    u | v
    a | b
    b | c
    c | a
    c | d
    d | a
    """)
    ranks = pw.stdlib.graphs.pagerank(edges, steps=15)
    caps1, _ = _run_n([ranks], 1)
    capsN, _ = _run_n([ranks], N_WORKERS)
    assert _snap(caps1[0]) == _snap(capsN[0])


def test_concat_and_distinct_universes_sharded():
    a = T("""
    k | x
    p | 1
    q | 2
    """)
    b = T("""
    k | x
    r | 3
    s | 4
    """)
    c = a.concat_reindex(b)
    caps1, _ = _run_n([c], 1)
    capsN, _ = _run_n([c], N_WORKERS)
    assert _snap(caps1[0]) == _snap(capsN[0])


def test_order_sensitive_ops_identical_across_workers():
    # dedup acceptance and earliest/latest tiebreaks use a canonical
    # per-tick order, so exchange partitioning cannot change results
    rows = "\n".join(f"r{i} | g | {i} | {2 * (1 + i // 6)}" for i in range(16))
    t = T("r | g | x | _time\n" + rows)
    ded = t.deduplicate(value=t.x, acceptor=lambda new, old: new > old)
    el = t.groupby(t.g).reduce(
        t.g, e=pw.reducers.earliest(t.x), l=pw.reducers.latest(t.x))
    caps1, _ = _run_n([ded, el], 1)
    capsN, _ = _run_n([ded, el], N_WORKERS)
    for c1, cN in zip(caps1, capsN):
        assert _stream(c1) == _stream(cN)


_MP_PROGRAM = """
import json
import os
import sys

import pathway_tpu as pw

class S(pw.Schema):
    shop: str
    item: str
    qty: int

class I(pw.Schema):
    item: str
    price: int

from pathway_tpu.debug import table_from_rows
from pathway_tpu.engine.multiproc import get_cluster
from pathway_tpu.internals.runner import GraphRunner

rows = []
for i in range(60):
    rows.append((f"s{i % 7}", f"i{i % 13}", i % 9, 2 * (i % 4), 1))
    if i % 11 == 0 and i > 0:
        rows.append(rows[i - 2][:3] + (2 * (i % 4) + 2, -1))
sales = table_from_rows(S, rows, is_stream=True)
info = table_from_rows(I, [(f"i{j}", 10 * (j + 1)) for j in range(13)])
totals = sales.groupby(sales.item).reduce(
    sales.item, qty=pw.reducers.sum(sales.qty), n=pw.reducers.count())
joined = totals.join(info, totals.item == info.item).select(
    totals.item, revenue=totals.qty * info.price)

runner = GraphRunner()
caps = [runner.capture(t) for t in (totals, joined)]
cl = get_cluster()
runner.run_batch(cluster=cl)
out = [sorted((int(k), repr(r), t, d)
              for k, r, t, d in c.consolidated_events()) for c in caps]
# run_batch executes one tick per distinct feed time (incl. 0) plus the
# end-of-stream flush tick — recorded so the test can pin the scheduler's
# STATIC round estimate against the rounds the cluster actually counted
_, feed_times = runner.static_feeds_by_time()
doc = {"caps": out,
       "transports": cl.transport_counts() if cl is not None else {},
       "stats": cl.stats if cl is not None else {},
       "ticks": len({0} | feed_times) + 1,
       "rounds_est": runner._scheduler.exchange_rounds_per_tick()}
with open(sys.argv[1], "w") as f:
    json.dump(doc, f)
"""


@pytest.mark.parametrize("transport,first_port",
                         [("tcp", 19310), ("shm", 19340)])
def test_multi_process_batch_matches_single(tmp_path, transport, first_port):
    """True multi-process execution (engine/multiproc.py): 2 OS processes
    exchange over the requested transport (raw TCP sockets, or the
    shared-memory slab ring with its socket doorbell); the union of their
    captured shards must equal the single-process result, the shards must
    be disjoint (state really partitioned across processes), and the
    forced transport must actually have carried the frames."""
    import json
    import subprocess
    import sys as _sys

    prog = tmp_path / "mp_prog.py"
    prog.write_text(_MP_PROGRAM)
    base_env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo",
                    PATHWAY_RUN_ID=f"mp-test-{transport}",
                    PATHWAY_EXCHANGE_TRANSPORT=transport)

    def run_procs(n: int, port: int) -> list[dict]:
        handles = []
        for pid in range(n):
            env = dict(base_env, PATHWAY_PROCESSES=str(n),
                       PATHWAY_PROCESS_ID=str(pid),
                       PATHWAY_THREADS="2",
                       PATHWAY_FIRST_PORT=str(port))
            handles.append(subprocess.Popen(
                [_sys.executable, str(prog), str(tmp_path / f"out_{n}_{pid}")],
                env=env, stderr=subprocess.PIPE, text=True))
        outs = []
        for h in handles:
            _, err = h.communicate(timeout=120)
            assert h.returncode == 0, err
        for pid in range(n):
            outs.append(json.loads(
                (tmp_path / f"out_{n}_{pid}").read_text()))
        return outs

    [single] = run_procs(1, first_port)
    shards = run_procs(2, first_port + 10)
    for doc in shards:
        assert doc["transports"] == {transport: 1}
        assert doc["stats"]["rows_out"] > 0
        # the static estimate (exchange_rounds_per_tick) re-states the
        # step loop's batching rules; this pins it to the rounds the
        # cluster ACTUALLY paid so the two copies cannot silently drift
        assert doc["rounds_est"] > 0
        assert doc["stats"]["rounds"] == doc["rounds_est"] * doc["ticks"]
        if transport == "shm":
            # the slab carried the payloads; sockets carried doorbells
            slab = (doc["stats"]["shm_bytes_out"]
                    + doc["stats"]["shm_bytes_in"])
            assert slab > doc["stats"]["bytes_out"]
    for cap_i in range(len(single["caps"])):
        merged = sorted(tuple(e) for s in shards
                        for e in s["caps"][cap_i])
        expect = sorted(tuple(e) for e in single["caps"][cap_i])
        assert merged == expect
        keys0 = {e[0] for e in shards[0]["caps"][cap_i]}
        keys1 = {e[0] for e in shards[1]["caps"][cap_i]}
        assert not (keys0 & keys1)
        assert keys0 and keys1


def test_external_index_sharded_queries_local_data_broadcast():
    """Index op under sharding (reference operators/external_index.rs:97 —
    data broadcast, queries local): results identical at n ∈ {1, 8}, the
    worker replicas share ONE index object (no per-worker slab copies),
    and several replicas answer queries (parallel answering)."""
    from pathway_tpu.engine.index_ops import ExternalIndexOperator
    from pathway_tpu.stdlib.indexing import DataIndex, TantivyBM25

    def build():
        docs = T("""
        text         | _time
        alpha_one    | 2
        beta_two     | 2
        gamma_three  | 4
        alpha_four   | 4
        """)
        rows = "\n".join(
            f"q{i} | {w} | 4" for i, w in enumerate(
                ["alpha_one", "beta_two", "gamma_three", "alpha_four"] * 4))
        queries = T("q | text | _time\n" + rows)
        index = DataIndex(docs, TantivyBM25(docs.text))
        res = index.query_as_of_now(queries.text, number_of_matches=1)
        return res.select(hit=res.text)

    caps1, _ = _run_n([build()], 1)
    capsN, runner = _run_n([build()], N_WORKERS)
    assert _stream(caps1[0]) == _stream(capsN[0])

    sched = runner._scheduler
    node = next(n for n in runner.graph.nodes
                if isinstance(n.op, ExternalIndexOperator))
    reps = sched._replicas[node.id]
    assert len(reps) == N_WORKERS
    # one shared index object across replicas; only replica 0 maintained it
    assert all(r.index is reps[0].index for r in reps)
    assert reps[0]._is_primary and not any(r._is_primary for r in reps[1:])
    answered = [r for r in reps if r.answers]
    assert len(answered) >= 2, "queries not answered in parallel"


def test_gradual_broadcast_sharded_matches_single():
    rows = T("k | x\n" + "\n".join(f"r{i} | {i}" for i in range(24)))
    thr = T("""
    lo | val | hi | _time
    0  | 5   | 10 | 2
    0  | 7   | 10 | 4
    """)
    out = rows._gradual_broadcast(thr, thr.lo, thr.val, thr.hi)
    caps1, _ = _run_n([out], 1)
    capsN, runner = _run_n([out], N_WORKERS)
    assert _stream(caps1[0]) == _stream(capsN[0])
    # rows are actually sharded now (no gather): several replicas hold rows
    from pathway_tpu.engine.operators import GradualBroadcastOperator

    sched = runner._scheduler
    node = next(n for n in runner.graph.nodes
                if isinstance(n.op, GradualBroadcastOperator))
    reps = sched._replicas[node.id]
    assert len(reps) == N_WORKERS
    assert sum(1 for r in reps if r.rows) >= 2


def test_iterate_inner_rounds_sharded():
    edges = T("""
    u | v
    a | b
    b | c
    c | a
    c | d
    d | a
    """)
    ranks = pw.stdlib.graphs.pagerank(edges, steps=15)
    runner = GraphRunner()
    cap = runner.capture(ranks)
    runner.run_batch(n_workers=N_WORKERS)
    from pathway_tpu.engine.graph import IterateOperator

    sched = runner._scheduler
    node = next(n for n in runner.graph.nodes
                if isinstance(n.op, IterateOperator))
    assert node.op.inner_workers == N_WORKERS
    # and the result still matches the single-worker run
    runner1 = GraphRunner()
    cap1 = runner1.capture(pw.stdlib.graphs.pagerank(T("""
    u | v
    a | b
    b | c
    c | a
    c | d
    d | a
    """), steps=15))
    runner1.run_batch(n_workers=1)
    assert _snap(cap) == _snap(cap1)


_MP_DYING = """
import os
import sys
import time

import pathway_tpu as pw
from pathway_tpu.debug import table_from_rows
from pathway_tpu.engine.multiproc import get_cluster
from pathway_tpu.internals.runner import GraphRunner

class S(pw.Schema):
    k: str
    x: int

rows = [(f"k{i}", i, 2 * (1 + i // 10), 1) for i in range(100)]
t = table_from_rows(S, rows, is_stream=True)
g = t.groupby(t.k).reduce(t.k, s=pw.reducers.sum(t.x))
runner = GraphRunner()
runner.capture(g)
if os.environ["PATHWAY_PROCESS_ID"] == "1" and "--die" in sys.argv:
    # simulate a crash after connecting but before finishing the run
    cl = get_cluster()
    time.sleep(0.3)
    os._exit(17)
runner.run_batch(cluster=get_cluster())
print("survived", flush=True)
"""


def test_cluster_peer_death_detected(tmp_path):
    """Failure detection (SURVEY §5): when one process of a cluster dies
    mid-run, its peers must FAIL (EOFError at the next exchange) rather
    than hang — the analogue of the reference's cross-worker panic
    propagation (dataflow.rs:5459-5601)."""
    import subprocess
    import sys as _sys

    prog = tmp_path / "dying.py"
    prog.write_text(_MP_DYING)
    env_base = dict(os.environ, JAX_PLATFORMS="cpu",
                    PYTHONPATH="/root/repo", PATHWAY_RUN_ID="mp-die")
    handles = []
    for pid in range(2):
        env = dict(env_base, PATHWAY_PROCESSES="2",
                   PATHWAY_PROCESS_ID=str(pid), PATHWAY_THREADS="1",
                   PATHWAY_FIRST_PORT="19710")
        args = [_sys.executable, str(prog)]
        if pid == 1:
            args.append("--die")
        handles.append(subprocess.Popen(args, env=env,
                                        stdout=subprocess.PIPE,
                                        stderr=subprocess.PIPE, text=True))
    out0, err0 = handles[0].communicate(timeout=60)
    out1, _err1 = handles[1].communicate(timeout=60)
    assert handles[1].returncode == 17          # the simulated crash
    assert handles[0].returncode != 0, out0     # peer fails, not hangs
    assert "survived" not in out0
    assert ("EOFError" in err0 or "Connection" in err0
            or "BrokenPipe" in err0 or "closed" in err0), err0[-500:]


def test_exchange_payload_wire_roundtrip():
    """The columnar exchange wire format must be lossless, including
    nested rows/bcast shapes and Pointer-keyed entries (engine/wire.py),
    and the frame must take the columnar kind for entry payloads."""
    from pathway_tpu.engine import wire
    from pathway_tpu.internals.keys import Pointer, hash_values

    ents = [(hash_values("a", i), (f"w{i}", i, None), 1 - 2 * (i % 2))
            for i in range(50)]
    payload = {"rows": {1: {3: ents}}, "wm": 7,
               "bcast": {0: ents[:3]}, "any": True}
    chunks, total, n_rows = wire.encode_frame(("x", 2, 0), payload)
    blob = b"".join(chunks)
    assert total == len(blob)
    assert blob[3] == wire.KIND_COLUMNAR
    assert n_rows == 50  # bcast and wm side-channels excluded
    tag, out, _ = wire.decode_frame(blob)
    assert tag == ("x", 2, 0)
    assert out == payload
    assert all(isinstance(e[0], Pointer) for e in out["rows"][1][3])
    # non-entry lists and scalars pass through untouched
    chunks2, _t, _n = wire.encode_frame("s", {"xs": [1, 2], "s": "x"})
    assert wire.decode_frame(b"".join(chunks2))[1] == \
        {"xs": [1, 2], "s": "x"}


def test_no_phantom_events_for_netzero_pairs_sharded():
    """A projection-collapsed net-zero pair must not surface phantom
    delete+insert events from a sharded join to subscribers."""
    t = T("""
    k | keep | drop | _time | _diff
    a | 1    | 10   | 2     | 1
    a | 1    | 10   | 4     | -1
    a | 1    | 11   | 4     | 1
    """)
    proj = t.select(t.k, t.keep)  # drops the changed column -> net-zero
    lex = T("""
    k | cat
    a | x
    """)
    for mode in ("join", "join_left", "join_outer"):
        j = getattr(proj, mode)(lex, proj.k == lex.k).select(
            proj.keep, lex.cat)
        caps1, _ = _run_n([j], 1)
        capsN, _ = _run_n([j], N_WORKERS)
        assert _stream(caps1[0]) == _stream(capsN[0]), mode


def test_tumbling_fast_path_matches_generic_assignment():
    """The arithmetic tumbling fast path must emit exactly what the
    generic flatten path does — pinned by comparing against
    sliding(hop=duration), which is semantically identical tumbling but
    takes the generic path (incl. retractions and negative times)."""
    t = T("""
    sensor | v | at  | _time | _diff
    a      | 1 | -7  | 2     | 1
    b      | 2 | 0   | 2     | 1
    a      | 3 | 4   | 4     | 1
    b      | 4 | 5   | 4     | 1
    a      | 3 | 4   | 6     | -1
    a      | 5 | 13  | 6     | 1
    """)

    def agg(win):
        return pw.temporal.windowby(
            t, t.at, window=win, instance=t.sensor,
        ).reduce(
            sensor=pw.this._pw_instance,
            start=pw.this._pw_window_start,
            end=pw.this._pw_window_end,
            s=pw.reducers.sum(pw.this.v),
        )

    for kw in ({}, {"offset": 3}, {"origin": -2}):
        fast = agg(pw.temporal.tumbling(4, **kw))
        generic = agg(pw.temporal.sliding(hop=4, duration=4, **kw))
        for n in (1, N_WORKERS):  # tuple-keyed sharding included
            caps, _ = _run_n([fast, generic], n)
            assert _stream(caps[0]) == _stream(caps[1]), (kw, n)
            assert _snap(caps[0]) == _snap(caps[1]), (kw, n)


def test_tumbling_fast_path_float_times():
    t = T("""
    v | at
    1 | 0.5
    2 | 3.9
    3 | 4.1
    """)
    win = pw.temporal.windowby(
        t, t.at + 0.0, window=pw.temporal.tumbling(2.0),
    ).reduce(start=pw.this._pw_window_start,
             s=pw.reducers.count())
    caps, _ = _run_n([win], 1)
    got = sorted(r for r in caps[0].snapshot().values())
    assert got == [(0.0, 1), (2.0, 1), (4.0, 1)]
