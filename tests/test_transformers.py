"""Row transformers, pandas_transformer, table_transformer
(reference: internals/row_transformer.py, stdlib/utils/pandas_transformer.py,
internals/common.py table_transformer)."""

import pytest

import pathway_tpu as pw
from tests.utils import T, rows_of


def test_class_transformer_basic():
    @pw.transformer
    class doubler:
        class table(pw.ClassArg):
            value = pw.input_attribute()

            @pw.output_attribute
            def doubled(self):
                return self.value * 2

            @pw.output_attribute
            def plus_one(self):
                return self.doubled + 1  # depends on another output attr

    t = T("""
    value
    3
    5
    """)
    result = doubler(table=t).table
    assert sorted(rows_of(result)) == [(6, 7), (10, 11)]
    # output keyed like the input: joinable back
    j = t.join(result, t.id == result.id).select(v=t.value, d=result.doubled)
    assert sorted(rows_of(j)) == [(3, 6), (5, 10)]


def test_class_transformer_pointer_chasing():
    @pw.transformer
    class chained:
        class nodes(pw.ClassArg):
            nxt = pw.input_attribute()
            val = pw.input_attribute()

            @pw.output_attribute
            def chain_sum(self):
                # sum of own value + next's value (pointer chase)
                if self.nxt is None:
                    return self.val
                other = self.transformer.nodes[self.nxt]
                return self.val + other.val

    t = T("""
    name | val
    a    | 1
    b    | 10
    c    | 100
    """).with_id_from(pw.this.name)
    linked = t.select(
        val=t.val,
        nxt=pw.if_else(t.name == "c", None,
                       t.pointer_from(pw.if_else(t.name == "a", "b", "c"))))
    result = chained(nodes=linked).nodes
    got = dict((v, s) for v, s in
               rows_of(linked.join(result, linked.id == result.id).select(
                   v=linked.val, s=result.chain_sum)))
    assert got == {1: 11, 10: 110, 100: 100}


def test_class_transformer_recursive_output_across_rows():
    @pw.transformer
    class cascade:
        class items(pw.ClassArg):
            nxt = pw.input_attribute()
            val = pw.input_attribute()

            @pw.output_attribute
            def total(self):
                # recursive: total = val + next.total
                if self.nxt is None:
                    return self.val
                return self.val + self.transformer.items[self.nxt].total

    t = T("""
    name | val
    a    | 1
    b    | 2
    c    | 4
    """).with_id_from(pw.this.name)
    linked = t.select(
        val=t.val,
        nxt=pw.if_else(t.name == "c", None,
                       t.pointer_from(pw.if_else(t.name == "a", "b", "c"))))
    result = cascade(items=linked).items
    got = dict(rows_of(linked.join(result, linked.id == result.id).select(
        v=linked.val, s=result.total)))
    assert got == {1: 7, 2: 6, 4: 4}


def test_pandas_transformer():
    schema = pw.schema_from_types(scaled=float)

    @pw.pandas_transformer(output_schema=schema, output_universe=0)
    def scale(df):
        return (df[["x"]] / df["x"].sum()).rename(columns={"x": "scaled"})

    t = T("""
    x
    1
    3
    """)
    result = scale(t)
    assert sorted(rows_of(result)) == [(0.25,), (0.75,)]
    # keys preserved (output_universe=first arg)
    j = t.join(result, t.id == result.id).select(x=t.x, s=result.scaled)
    assert sorted(rows_of(j)) == [(1, 0.25), (3, 0.75)]


def test_table_transformer_checks_schema():
    class NeedsX(pw.Schema):
        x: int

    @pw.table_transformer
    def f(t: NeedsX):
        return t

    t_ok = T("""
    x | y
    1 | 2
    """)
    f(t_ok)  # superset allowed
    t_bad = T("""
    z
    1
    """)
    with pytest.raises(TypeError, match="missing"):
        f(t_bad)


def test_show_and_repr_html_and_interactive():
    t = T("""
    a
    1
    2
    """)
    rendered = t.show()
    assert "a" in rendered and "1" in rendered
    html = t._repr_html_()
    assert html.startswith("<table>") and "<td>2</td>" in html

    import sys
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ctrl = pw.enable_interactive_mode()
    try:
        assert pw.is_interactive_mode_enabled()
        import io
        buf = io.StringIO()
        stdout, sys.stdout = sys.stdout, buf
        try:
            sys.displayhook(t)
        finally:
            sys.stdout = stdout
        assert "a" in buf.getvalue()
    finally:
        ctrl.close()
        from pathway_tpu.internals.parse_graph import G
        G.interactive_mode_controller = None
