"""OTel telemetry (reference: src/engine/telemetry.rs:196-366 +
graph_runner/telemetry.py spans): instrumentation flows through the OTel
API — spans and observable gauges are exercised against an in-memory
tracer/meter double, and pw.run stays correct with telemetry enabled and
no SDK installed (no-op path)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.telemetry import Config, Telemetry


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


def test_config_env_activation(monkeypatch):
    monkeypatch.delenv("PATHWAY_TELEMETRY_ENDPOINT", raising=False)
    assert not Config.create().telemetry_enabled
    monkeypatch.setenv("PATHWAY_TELEMETRY_ENDPOINT", "http://otlp:4317")
    cfg = Config.create()
    assert cfg.telemetry_enabled and cfg.endpoint == "http://otlp:4317"


def test_spans_and_gauges_through_api_doubles(monkeypatch):
    """Drive the instrumentation against recording tracer/meter doubles —
    proves real attributes/observations flow through the OTel API."""
    spans = []

    class _Span:
        def __init__(self, name):
            self.name = name
            self.attrs = {}

        def set_attribute(self, k, v):
            self.attrs[k] = v

        def __enter__(self):
            spans.append(self)
            return self

        def __exit__(self, *a):
            return False

    class _Tracer:
        def start_as_current_span(self, name):
            return _Span(name)

    gauges = {}

    class _Meter:
        def create_observable_gauge(self, name, callbacks=None, **kw):
            gauges[name] = callbacks
            return name

        def create_observable_counter(self, name, callbacks=None, **kw):
            gauges[name] = callbacks
            return name

    tel = Telemetry(Config.create())
    tel.tracer = _Tracer()
    tel.meter = _Meter()
    tel._instruments = {}

    with tel.span("pathway.run", run_id="r1") as sp:
        assert sp.name == "pathway.run" and sp.attrs["run_id"] == "r1"
    assert [s.name for s in spans] == ["pathway.run"]

    # wire gauges over a real scheduler after a real run
    t = pw.debug.table_from_markdown("""
    a | b
    1 | 2
    3 | 4
    """)
    agg = t.groupby(t.b).reduce(t.b, s=pw.reducers.sum(t.a))
    from pathway_tpu.internals.runner import GraphRunner

    runner = GraphRunner()
    runner.capture(agg)
    runner.run_batch()
    tel.register_scheduler_gauges(runner._scheduler, runner.graph)
    assert "pathway.operator.latency_ms" in gauges
    obs = gauges["pathway.operator.insertions"][0](None)
    assert sum(o.value for o in obs) > 0
    mem = gauges["pathway.process.memory_bytes"][0](None)
    assert mem[0].value > 1 << 20


def test_run_with_telemetry_enabled_noop_sdk():
    """pw.run(telemetry_config=...) with no SDK installed must work and
    produce correct results (API no-op path)."""
    t = pw.debug.table_from_markdown("""
    x
    1
    2
    """)
    doubled = t.select(y=t.x * 2)
    got = pw.debug.table_to_pandas(
        doubled, include_id=False)["y"].tolist()
    assert sorted(got) == [2, 4]
    # and through pw.run with an output binder
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        pw.io.jsonlines.write(doubled, f"{d}/out.jsonl")
        pw.run(telemetry_config=Config.create(telemetry_enabled=True))
        import json

        rows = [json.loads(line) for line in
                open(f"{d}/out.jsonl").read().splitlines()]
        assert sorted(r["y"] for r in rows) == [2, 4]
