"""Fleet observability plane (engine/fleet_observability.py, PR 14):
cross-process request-id propagation, the clock-aligned trace merge,
the router's fleet surfaces, the perf-trajectory regression watch, and
the atomic-write directory-fsync durability fix."""

from __future__ import annotations

import http.client
import http.server
import json
import os
import socket
import threading
import time
import urllib.request

import pytest

from pathway_tpu.engine import fleet_observability as fo
from pathway_tpu.engine.flight_recorder import atomic_write_json
from pathway_tpu.testing import faults


# ---------------------------------------------------------------------------
# request-id propagation
# ---------------------------------------------------------------------------

def test_adopt_request_id_sanitizes_and_adopts():
    from pathway_tpu.io.http import _adopt_request_id

    assert _adopt_request_id("rtr-1a2b-000007") == "rtr-1a2b-000007"
    assert _adopt_request_id("a.b:c_d-e") == "a.b:c_d-e"
    # junk must not leak into traces/labels: minted instead
    for bad in (None, "", "   ", "has space", 'quo"te', "new\nline",
                "x" * 200):
        rid = _adopt_request_id(bad)
        assert rid != bad and "-" in rid


def test_webserver_adopts_inbound_request_id():
    """The serving process adopts the router's id instead of minting its
    own — the contract that makes ONE id name a query end to end."""
    from pathway_tpu.io.http import PathwayWebserver

    ws = PathwayWebserver(host="127.0.0.1", port=0)

    async def handler(payload):
        return {"ok": True}

    ws.register("/echo", ("POST",), handler, None)
    ws.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{ws.port}/echo", data=b"{}",
            headers={"Content-Type": "application/json",
                     "X-Pathway-Request-Id": "rtr-ffff-000042"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers["X-Pathway-Request-Id"] == \
                "rtr-ffff-000042"
        # an unsafe inbound id is replaced, and the replacement is echoed
        req = urllib.request.Request(
            f"http://127.0.0.1:{ws.port}/echo", data=b"{}",
            headers={"Content-Type": "application/json",
                     "X-Pathway-Request-Id": 'bad id with "junk"'})
        with urllib.request.urlopen(req, timeout=10) as resp:
            rid = resp.headers["X-Pathway-Request-Id"]
            assert rid and rid != 'bad id with "junk"'
    finally:
        pass  # webserver threads are daemonic; no teardown surface


def _make_router(**kw):
    from pathway_tpu.engine.router import QueryRouter

    router = QueryRouter(port=0, control_port=0, **kw)
    router.start()
    return router


def _post(port: int, path: str, headers: dict,
          body: bytes = b"{}") -> http.client.HTTPResponse:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", path, body=body,
                 headers={"Content-Type": "application/json", **headers})
    return conn.getresponse()


def test_router_echoes_request_id_on_503():
    """Satellite pin: an unroutable query's 503 still carries the id the
    client sent — a lost query stays greppable fleet-wide."""
    router = _make_router()
    try:
        resp = _post(router.port, "/q",
                     {"X-Pathway-Request-Id": "rtr-dead-000001"})
        body = resp.read()
        assert resp.status == 503, body
        assert resp.headers["X-Pathway-Request-Id"] == "rtr-dead-000001"
        # a query that arrived without an id gets one minted AT the
        # router and echoed, even on the 503
        resp = _post(router.port, "/q", {})
        resp.read()
        assert resp.status == 503
        assert resp.headers["X-Pathway-Request-Id"].startswith("rtr-")
    finally:
        router.stop()


class _CaptureBackend:
    """A one-route HTTP backend that records every request's headers."""

    def __init__(self):
        outer = self
        self.seen: list[dict] = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                outer.seen.append(dict(self.headers))
                length = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(length)
                body = b'{"ok": true}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self.httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _endpoint(router, rid: str, host: str, port: int):
    from pathway_tpu.engine.router import ReplicaEndpoint

    a, _b = socket.socketpair()
    ep = ReplicaEndpoint(rid, "replica", host, port, a)
    router._endpoints[rid] = ep
    return ep


def test_router_failover_replay_carries_same_id_and_hop():
    """Satellite pin: the failover replay forwards the SAME request id
    (plus the hop counter) to the rescuing replica, the response echoes
    it, and the router-side span records forward(fail) + failover(ok)."""
    backend = _CaptureBackend()
    # a dead endpoint: bind a listener and close it -> connection refused
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    dead_port = dead.getsockname()[1]
    dead.close()
    router = _make_router()
    try:
        _endpoint(router, "r-dead", "127.0.0.1", dead_port)
        _endpoint(router, "r-live", "127.0.0.1", backend.port)
        resp = _post(router.port, "/q",
                     {"X-Pathway-Request-Id": "rtr-abcd-000009"})
        data = resp.read()
        assert resp.status == 200, data
        assert resp.headers["X-Pathway-Request-Id"] == "rtr-abcd-000009"
        assert resp.headers["X-Pathway-Failovers"] == "1"
        assert resp.headers["X-Pathway-Replica"] == "r-live"
        # the rescuing replica received the SAME id with hop 0 -> 1
        assert len(backend.seen) == 1
        seen = backend.seen[0]
        assert seen["X-Pathway-Request-Id"] == "rtr-abcd-000009"
        assert seen["X-Pathway-Hop"] == "1"
        # router-side span: route + failed forward + rescuing failover
        spans = list(router.request_log.completed)
        assert len(spans) == 1
        span = spans[0]
        assert span.rid == "rtr-abcd-000009"
        assert span.replica == "r-live" and span.failovers() == 1
        stages = [(s, r, ok) for s, r, _t0, _t1, ok in span.attempts]
        assert stages == [("forward", "r-dead", False),
                          ("failover", "r-live", True)]
    finally:
        router.stop()
        backend.stop()


def test_router_p50_skew_metric_exposed():
    """Satellite: router-observed vs replica-self-reported p50 skew is a
    per-replica gauge — a clock-drifted or overloaded replica shows up
    before it breaches SLO."""
    router = _make_router()
    try:
        ep = _endpoint(router, "r1", "127.0.0.1", 1)
        for ms in (10.0, 10.0, 10.0, 10.0, 10.0, 10.0):
            ep.observe(ms)
        ep.reported_p50_ms = 4.0
        assert ep.p50_skew_ms() == pytest.approx(6.0)
        metrics = router.metrics_payload()
        assert ('pathway_tpu_router_replica_p50_skew_ms{replica="r1"} '
                "6.0") in metrics
        assert "# TYPE pathway_tpu_router_replica_p50_skew_ms gauge" \
            in metrics
        # without a self-report there is no skew sample (absent, not 0)
        ep.reported_p50_ms = None
        assert "p50_skew_ms" not in router.metrics_payload().replace(
            "# TYPE pathway_tpu_router_replica_p50_skew_ms gauge", "")
    finally:
        router.stop()


def test_fleet_status_one_json(monkeypatch):
    router = _make_router()
    try:
        ep = _endpoint(router, "r1", "127.0.0.1", 1)
        ep.apply_heartbeat({"applied_tick": 41, "staleness_ticks": 3,
                            "generation": 2, "burn_rate": 0.25,
                            "p50_ms": 4.0})
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/fleet/status",
            timeout=10).read())
        assert st["role"] == "router"
        assert "burn_rate" in st
        assert set(st["request_stages"]) == {"route", "forward",
                                             "failover"}
        (member,) = st["fleet"]
        assert member["replica"] == "r1"
        assert member["applied_tick"] == 41
        assert member["staleness_ticks"] == 3
        assert member["burn_rate"] == 0.25
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# trace merge
# ---------------------------------------------------------------------------

def _router_payload(rid="abc", epoch_wall_us=1_000_000.0):
    return {
        "traceEvents": [
            {"ph": "M", "pid": 0, "tid": 0, "name": "thread_name",
             "args": {"name": "router requests"}},
            {"ph": "b", "cat": "router_request", "id": f"req-{rid}",
             "pid": 0, "tid": 0, "ts": 500_000.0, "name": f"req {rid}",
             "args": {"request_id": rid, "failovers": 1}},
            {"ph": "e", "cat": "router_request", "id": f"req-{rid}",
             "pid": 0, "tid": 0, "ts": 700_000.0, "name": f"req {rid}"},
        ],
        "displayTimeUnit": "ms",
        "pathway_meta": {"pid": 101, "process": "router",
                         "role": "router",
                         "epoch_wall_us": epoch_wall_us},
    }


def _serving_payload(rid="abc", process="r2", epoch_wall_us=2_000_000.0):
    return {
        "traceEvents": [
            {"ph": "b", "cat": "request", "id": f"req-{rid}", "pid": 0,
             "tid": 2, "ts": 0.0, "name": f"req {rid}",
             "args": {"request_id": rid}},
            {"ph": "e", "cat": "request", "id": f"req-{rid}", "pid": 0,
             "tid": 2, "ts": 90_000.0, "name": f"req {rid}"},
        ],
        "displayTimeUnit": "ms",
        "pathway_meta": {"pid": 202, "process": process,
                         "role": "replica",
                         "epoch_wall_us": epoch_wall_us},
    }


def test_merge_traces_aligns_clocks_and_links_processes():
    merged = fo.merge_traces([_router_payload(), _serving_payload()])
    events = merged["traceEvents"]
    fleet = merged["pathway_fleet"]
    assert [p["role"] for p in fleet["processes"]] == ["router",
                                                       "replica"]
    assert fleet["cross_process_request_ids"] == ["abc"]
    # distinct merged pids, named process tracks
    names = {e["pid"]: e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert set(names.values()) == {"router:router", "replica:r2"}
    # clock alignment: origin is the earliest epoch (router, 1.0s); the
    # serving process's ts shift by the 1.0s epoch difference
    router_b = next(e for e in events
                    if e.get("cat") == "router_request"
                    and e["ph"] == "b")
    serving_b = next(e for e in events if e.get("cat") == "request"
                     and e["ph"] == "b")
    assert router_b["ts"] == pytest.approx(500_000.0)
    assert serving_b["ts"] == pytest.approx(1_000_000.0)
    assert router_b["pid"] != serving_b["pid"]
    # the cross-process flow arrow: s on the router's span, f on the
    # serving (rescuing) process's span
    s = next(e for e in events if e["ph"] == "s" and e["cat"] == "fleet")
    f = next(e for e in events if e["ph"] == "f" and e["cat"] == "fleet")
    assert s["id"] == f["id"] == "xreq-abc"
    assert s["pid"] == router_b["pid"]
    assert f["pid"] == serving_b["pid"]
    assert s["ts"] == pytest.approx(router_b["ts"])


def test_merge_traces_tolerates_missing_meta_and_empty():
    empty = fo.merge_traces([])
    assert empty["traceEvents"] == []
    assert empty["pathway_fleet"]["cross_process_request_ids"] == []
    bare = {"traceEvents": [{"ph": "B", "pid": 0, "tid": 0, "ts": 1.0,
                             "name": "x", "args": {}},
                            {"ph": "E", "pid": 0, "tid": 0, "ts": 2.0,
                             "name": "x"}]}
    merged = fo.merge_traces([bare, {"not": "a trace"}])
    # the metaless payload merges with offset 0 and an anonymous name
    assert len(merged["pathway_fleet"]["processes"]) == 1
    assert any(e["ph"] == "B" for e in merged["traceEvents"])


def test_merge_traces_nesting_preserved_per_process():
    """B/E nesting is per-(pid, tid): merging two processes that each
    nest correctly must yield a merged file that still validates under
    the PR-5 checker keyed by (pid, tid)."""
    def proc(epoch):
        return {
            "traceEvents": [
                {"ph": "B", "pid": 0, "tid": 0, "ts": 10.0,
                 "name": "tick 1", "args": {}},
                {"ph": "B", "pid": 0, "tid": 0, "ts": 11.0, "name": "op",
                 "args": {}},
                {"ph": "E", "pid": 0, "tid": 0, "ts": 12.0, "name": "op"},
                {"ph": "E", "pid": 0, "tid": 0, "ts": 13.0,
                 "name": "tick 1"},
            ],
            "pathway_meta": {"pid": 1, "process": "p", "role": "primary",
                             "epoch_wall_us": epoch},
        }

    merged = fo.merge_traces([proc(1e6), proc(5e6)])
    stacks: dict = {}
    for ev in merged["traceEvents"]:
        key = (ev["pid"], ev.get("tid", 0))
        if ev["ph"] == "B":
            stacks.setdefault(key, []).append(ev["name"])
        elif ev["ph"] == "E":
            assert stacks.get(key), f"E without B: {ev}"
            assert stacks[key].pop() == ev["name"]
    assert all(not s for s in stacks.values())


def test_router_request_log_chrome_events_shape():
    log = fo.RouterRequestLog()
    span = log.start("rid-1", "/q")
    span.note_routed()
    t = time.perf_counter()
    span.note_attempt("r-dead", t, ok=False)
    span.note_attempt("r-live", time.perf_counter(), ok=True)
    log.finish(span, 200, "r-live")
    events = log.chrome_trace_events()
    b = [e for e in events if e["ph"] == "b"]
    e_ = [e for e in events if e["ph"] == "e"]
    assert len(b) == len(e_) == 3  # request span + forward + failover
    top = next(ev for ev in b if ev["name"] == "req rid-1")
    assert top["args"]["request_id"] == "rid-1"
    assert top["args"]["failovers"] == 1
    assert {ev["name"] for ev in b} == {"req rid-1", "forward r-dead",
                                        "failover r-live"}
    summary = log.stage_summary()
    assert summary["failover"]["sum_ms"] >= 0.0


def test_trace_merge_cli(tmp_path):
    from click.testing import CliRunner

    from pathway_tpu.cli import cli

    (tmp_path / "router.json").write_text(json.dumps(_router_payload()))
    (tmp_path / "r2.json").write_text(json.dumps(_serving_payload()))
    (tmp_path / "junk.json").write_text("{\"no\": \"trace\"}")
    runner = CliRunner()
    res = runner.invoke(cli, ["trace-merge", str(tmp_path)])
    assert res.exit_code == 0, res.output
    merged = json.loads((tmp_path / "fleet_trace.json").read_text())
    assert merged["pathway_fleet"]["cross_process_request_ids"] == ["abc"]
    assert len(merged["pathway_fleet"]["processes"]) == 2
    # idempotent over its own output: a re-run must not merge the merge
    res = runner.invoke(cli, ["trace-merge", str(tmp_path)])
    assert res.exit_code == 0, res.output
    merged2 = json.loads((tmp_path / "fleet_trace.json").read_text())
    assert len(merged2["pathway_fleet"]["processes"]) == 2


# ---------------------------------------------------------------------------
# perf-trajectory watch
# ---------------------------------------------------------------------------

def _seed(path, leg, metric, values):
    for v in values:
        fo.append_bench_history(leg, {metric: v}, path=str(path),
                                sha="deadbeef")


def test_history_append_and_read(tmp_path):
    path = tmp_path / "hist.jsonl"
    n = fo.append_bench_history(
        "etl", {"etl_rows_per_s": 100.0, "skip_me": "text",
                "flag": True, "count": 7}, path=str(path), sha="abc123")
    assert n == 2  # the string and the bool are skipped
    # a torn tail line is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"leg": "etl", "metric": "torn')
    rows = fo.bench_history_rows(str(path))
    assert [(r["metric"], r["value"]) for r in rows] == \
        [("count", 7.0), ("etl_rows_per_s", 100.0)]
    assert all(r["sha"] == "abc123" for r in rows)


def test_regression_flags_seeded_drop_not_noise(tmp_path):
    path = tmp_path / "hist.jsonl"
    _seed(path, "etl", "etl_rows_per_s", [100, 104, 97, 101, 99])
    assert fo.check_regressions(str(path)) == []
    # within-band noise passes...
    _seed(path, "etl", "etl_rows_per_s", [85])
    assert fo.check_regressions(str(path)) == []
    # ...a genuine drop past the band is flagged against the MEDIAN
    _seed(path, "etl", "etl_rows_per_s", [40])
    regs = fo.check_regressions(str(path))
    assert len(regs) == 1
    r = regs[0]
    assert (r["leg"], r["metric"]) == ("etl", "etl_rows_per_s")
    assert r["direction"] == "higher" and r["ratio"] < 0.65


def test_regression_lower_better_and_young_series(tmp_path):
    path = tmp_path / "hist.jsonl"
    # young series (fewer than min_prior prior points) never gates
    _seed(path, "serving", "knn_p50_e2e_ms", [5.0, 90.0])
    assert fo.check_regressions(str(path)) == []
    _seed(path, "serving", "knn_p50_e2e_ms", [5.1, 4.9])
    # now 3 prior points exist and the newest (4.9) is fine
    assert fo.check_regressions(str(path), window=2) == []
    _seed(path, "serving", "knn_p50_e2e_ms", [30.0])
    regs = fo.check_regressions(str(path))
    assert regs and regs[0]["direction"] == "lower"


def test_regression_tolerance_band_and_unwatched(tmp_path):
    path = tmp_path / "hist.jsonl"
    _seed(path, "x", "docs_per_s", [100, 100, 100, 80])
    # 20% drop: flagged at a 10% band, passes at the default 35%
    assert fo.check_regressions(str(path)) == []
    assert fo.check_regressions(str(path), tolerance=0.10)
    # per-metric override wins over the default
    assert fo.check_regressions(
        str(path), tolerances={"docs_per": 0.05})
    # a metric with no recognizable direction is unwatched
    _seed(path, "x", "mystery_number", [1, 1, 1, 1000])
    flagged = {r["metric"] for r in fo.check_regressions(
        str(path), tolerance=0.10)}
    assert "mystery_number" not in flagged


def test_regression_zero_median_series(tmp_path):
    path = tmp_path / "hist.jsonl"
    _seed(path, "fleet", "replica_lost_queries", [0, 0, 0, 0])
    assert fo.check_regressions(str(path)) == []
    _seed(path, "fleet", "replica_lost_queries", [3])
    regs = fo.check_regressions(str(path))
    assert regs and regs[0]["metric"] == "replica_lost_queries"
    assert regs[0]["ratio"] is None  # infinite: any loss off a zero floor


def test_metric_direction_heuristics():
    assert fo.metric_direction("docs_per_s") == "higher"
    assert fo.metric_direction("etl_scaleout_efficiency") == "higher"
    assert fo.metric_direction("framework_vs_raw_ratio") == "higher"
    assert fo.metric_direction("knn_p50_e2e_ms") == "lower"
    assert fo.metric_direction("replica_ready_snapshot_s_1000") == "lower"
    assert fo.metric_direction("replica_max_staleness_ticks") == "lower"
    assert fo.metric_direction("router_replica_p50_skew_ms") == "lower"
    assert fo.metric_direction("knn_n_vectors") is None


# ---------------------------------------------------------------------------
# atomic_write_json directory fsync (satellite bugfix)
# ---------------------------------------------------------------------------

def test_atomic_write_fsyncs_containing_directory(tmp_path, monkeypatch):
    """The rename's durability lives in the directory's metadata: the
    write must fsync the containing dir after os.replace (the ext4
    crash-right-after-rename hole)."""
    synced_dirs: list[str] = []
    real_fsync = os.fsync

    def spy_fsync(fd):
        try:
            target = os.readlink(f"/proc/self/fd/{fd}")
            if os.path.isdir(target):
                synced_dirs.append(target)
        except OSError:
            pass
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", spy_fsync)
    path = tmp_path / "evidence.json"
    atomic_write_json(str(path), {"v": 1})
    assert json.loads(path.read_text()) == {"v": 1}
    assert str(tmp_path) in synced_dirs


def test_atomic_write_dirsync_crash_keeps_renamed_file(tmp_path):
    """Fault-point pin: a crash landing between the rename and the dir
    fsync (fs.atomic_write.dirsync) surfaces as the injected error, but
    the NEW content is already at the path — the rename itself happened
    before the crash window."""
    path = tmp_path / "evidence.json"
    atomic_write_json(str(path), {"v": 1})
    with faults.arm("fs.atomic_write.dirsync", faults.FailNTimes(1)):
        with pytest.raises(faults.InjectedFault):
            atomic_write_json(str(path), {"v": 2})
    assert json.loads(path.read_text()) == {"v": 2}
    # no tmp litter from the fault path
    assert [p.name for p in tmp_path.iterdir()] == ["evidence.json"]
    # disarmed, the write is clean again
    atomic_write_json(str(path), {"v": 3})
    assert json.loads(path.read_text()) == {"v": 3}


def test_bench_history_appends_survive_dirsync_fault(tmp_path):
    """BENCH_HISTORY appends are plain line appends (no rename), and the
    lastgood checkpoint path keeps its file through an injected dirsync
    crash — the satellite's end-to-end shape via bench's own writer."""
    import bench

    lastgood = tmp_path / "BENCH_LASTGOOD.json"
    old_state = dict(bench._LASTGOOD_STATE)
    bench._LASTGOOD_STATE.clear()
    old_env = os.environ.get("BENCH_LASTGOOD_PATH")
    os.environ["BENCH_LASTGOOD_PATH"] = str(lastgood)
    try:
        bench._write_lastgood({"etl_rows_per_s": 123.0})
        assert json.loads(lastgood.read_text())["result"][
            "etl_rows_per_s"] == 123.0
        with faults.arm("fs.atomic_write.dirsync", faults.FailNTimes(1)):
            # _write_lastgood swallows (evidence must never kill a leg)
            bench._write_lastgood({"etl_rows_per_s": 124.0})
        # the rename preceded the injected crash: newest value is live
        assert json.loads(lastgood.read_text())["result"][
            "etl_rows_per_s"] == 124.0
    finally:
        bench._LASTGOOD_STATE.clear()
        bench._LASTGOOD_STATE.update(old_state)
        if old_env is None:
            os.environ.pop("BENCH_LASTGOOD_PATH", None)
        else:
            os.environ["BENCH_LASTGOOD_PATH"] = old_env
