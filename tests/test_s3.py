"""Native SigV4 S3 client + connector + persistence backend against an
in-test S3-compatible server that VERIFIES the signature chain
(reference: rust-s3-backed S3Scanner data_storage.rs:1769 and the S3
persistence backends; here the protocol is implemented directly)."""

from __future__ import annotations

import hashlib
import hmac
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.s3 import AwsS3Settings
from pathway_tpu.io.s3._client import S3Client

ACCESS, SECRET, REGION = "AKTEST", "sekrit", "eu-test-1"


@pytest.fixture(autouse=True)
def _clear_graph():
    G.clear()
    yield
    G.clear()


class _FakeS3(BaseHTTPRequestHandler):
    objects: dict = {}  # (bucket, key) -> bytes
    verify_auth = True

    def log_message(self, *args):
        pass

    # -- SigV4 verification (the server-side half of the handshake) -------
    def _check_sig(self) -> bool:
        if not self.verify_auth:
            return True
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        fields = dict(p.strip().split("=", 1)
                      for p in auth.split(" ", 1)[1].split(","))
        signed = fields["SignedHeaders"].split(";")
        u = urlparse(self.path)
        cq = "&".join(sorted(u.query.split("&"))) if u.query else ""
        canonical = "\n".join([
            self.command, u.path, cq,
            "".join(f"{h}:{self.headers[h]}\n" for h in signed),
            fields["SignedHeaders"],
            self.headers["x-amz-content-sha256"],
        ])
        datestamp, region, service, _ = fields["Credential"].split(
            "/", 4)[1:]
        scope = f"{datestamp}/{region}/{service}/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", self.headers["x-amz-date"], scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        k = hmac.new(b"AWS4" + SECRET.encode(), datestamp.encode(),
                     hashlib.sha256).digest()
        for part in (region, service, "aws4_request"):
            k = hmac.new(k, part.encode(), hashlib.sha256).digest()
        want = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        return hmac.compare_digest(want, fields["Signature"])

    def _split(self):
        u = urlparse(self.path)
        parts = unquote(u.path).lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        return bucket, key, parse_qs(u.query)

    def _reply(self, code, body=b"", ctype="application/xml"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        if not self._check_sig():
            return self._reply(403)
        bucket, key, _ = self._split()
        n = int(self.headers.get("Content-Length", 0))
        self.objects[(bucket, key)] = self.rfile.read(n)
        self._reply(200)

    def do_GET(self):
        if not self._check_sig():
            return self._reply(403)
        bucket, key, q = self._split()
        if "list-type" in q:
            prefix = q.get("prefix", [""])[0]
            items = sorted(k for (b, k) in self.objects
                           if b == bucket and k.startswith(prefix))
            xml = ['<?xml version="1.0"?><ListBucketResult '
                   'xmlns="http://s3.amazonaws.com/doc/2006-03-01/">']
            for k in items:
                xml.append(
                    f"<Contents><Key>{k}</Key>"
                    f"<Size>{len(self.objects[(bucket, k)])}</Size>"
                    f"<LastModified>2026-07-30T00:00:00Z</LastModified>"
                    f"</Contents>")
            xml.append("<IsTruncated>false</IsTruncated></ListBucketResult>")
            return self._reply(200, "".join(xml).encode())
        data = self.objects.get((bucket, key))
        if data is None:
            return self._reply(404)
        self._reply(200, data, ctype="application/octet-stream")

    def do_DELETE(self):
        if not self._check_sig():
            return self._reply(403)
        bucket, key, _ = self._split()
        self.objects.pop((bucket, key), None)
        self._reply(204)


@pytest.fixture()
def fake_s3():
    _FakeS3.objects = {}
    server = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def _client(endpoint, bucket="pail"):
    return S3Client(bucket=bucket, access_key=ACCESS, secret_key=SECRET,
                    region=REGION, endpoint=endpoint)


def test_client_roundtrip_signed(fake_s3):
    c = _client(fake_s3)
    c.put_object("a/x.txt", b"hello")
    c.put_object("a/y.txt", b"world")
    c.put_object("b/z.txt", b"other")
    assert c.get_object("a/x.txt") == b"hello"
    assert c.get_object_or_none("missing") is None
    listed = [o["key"] for o in c.list_objects("a/")]
    assert listed == ["a/x.txt", "a/y.txt"]
    c.delete_object("a/x.txt")
    assert c.get_object_or_none("a/x.txt") is None


def test_client_bad_secret_rejected(fake_s3):
    c = S3Client(bucket="pail", access_key=ACCESS, secret_key="wrong",
                 region=REGION, endpoint=fake_s3)
    with pytest.raises(RuntimeError, match="403"):
        c.put_object("k", b"v")


def test_s3_connector_static_read(fake_s3):
    c = _client(fake_s3)
    c.put_object("docs/one.txt", b"first doc")
    c.put_object("docs/two.txt", b"second doc")
    c.put_object("other/three.txt", b"outside prefix")
    settings = AwsS3Settings(bucket_name="pail", access_key=ACCESS,
                             secret_access_key=SECRET, region=REGION,
                             endpoint=fake_s3)
    t = pw.io.s3.read("pail/docs", aws_s3_settings=settings, mode="static")
    rows = sorted(r[0] for r in
                  pw.debug.table_to_pandas(t).itertuples(index=False))
    assert rows == [b"first doc", b"second doc"]


def test_s3_persistence_backend_resume(fake_s3):
    """Commit a prefix to S3 objects, 'restart', and verify the durable
    records replay — the Backend.s3 path writes real objects now."""
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io._datasource import Session
    from pathway_tpu.io.python import ConnectorSubject, PythonSource

    settings = AwsS3Settings(bucket_name="pail", access_key=ACCESS,
                             secret_access_key=SECRET, region=REGION,
                             endpoint=fake_s3)
    cfg = pw.persistence.Config(
        backend=pw.persistence.Backend.s3("s3://pail/snapshots",
                                          bucket_settings=settings))
    schema = sch.schema_from_types(data=str)

    class _Subject(ConnectorSubject):
        def run(self):
            pass

    src = PythonSource(_Subject(), schema)
    src.persistent_id = "events"
    driver = PersistenceDriver(cfg)
    live = Session()
    rec = driver.attach_source(src, live)
    k, r = src.row_to_engine({"data": "alpha"}, 0)
    rec.push(k, r, 1)
    driver.commit(1)
    k, r = src.row_to_engine({"data": "beta"}, 1)
    rec.push(k, r, 1)
    driver.commit(2)
    driver.close()

    # the commits are visible as objects
    keys = [o["key"] for o in _client(fake_s3).list_objects("snapshots/")]
    assert keys == ["snapshots/streams/events/0000000000000000",
                    "snapshots/streams/events/0000000000000001"]

    # restart: replay the durable prefix
    src2 = PythonSource(_Subject(), schema)
    src2.persistent_id = "events"
    driver2 = PersistenceDriver(cfg)
    live2 = Session()
    driver2.attach_source(src2, live2)
    replayed = sorted(row[1][0] for row in live2.drain())
    assert replayed == ["alpha", "beta"]
    assert driver2.restore_time() == 2
    driver2.close()


def test_s3_log_truncates_at_torn_upload(fake_s3):
    """A torn object ends the durable prefix (the replay+skip resume
    protocol needs the replayed records to be a PREFIX of the reader's
    re-emitted sequence — a hole would desynchronize it), and the next
    run's append overwrites the torn slot, like the file log truncating
    its torn tail."""
    from pathway_tpu.engine.persistence import S3SnapshotLog

    c = _client(fake_s3)
    log = S3SnapshotLog(c, "snap", "src")
    log.append(1, [("k", ("a",), 1, None)])
    log.append(2, [("k2", ("b",), 1, None)])
    # simulate an interrupted upload: truncated body
    body = c.get_object("snap/streams/src/0000000000000001")
    c.put_object("snap/streams/src/0000000000000001", body[:-3])
    # driver flow on restart: read_all first, then appends resume
    log2 = S3SnapshotLog(c, "snap", "src")
    assert [t for t, _e in log2.read_all()] == [1]
    log2.append(3, [("k3", ("c",), 1, None)])
    assert [t for t, _e in S3SnapshotLog(c, "snap", "src").read_all()] \
        == [1, 3]


def test_s3_format_reads_csv_and_jsonlines(fake_s3):
    """Non-binary formats parse object payloads through the format layer
    (reference S3GenericReader scope: csv/json/plaintext)."""
    c = _client(fake_s3)
    c.put_object("fmt/a.csv", b"word,qty\nalpha,3\nbeta,4\n")
    c.put_object("fmt/b.csv", b"word,qty\ngamma,5\n")
    settings = AwsS3Settings(bucket_name="pail", access_key=ACCESS,
                             secret_access_key=SECRET, region=REGION,
                             endpoint=fake_s3)
    schema = pw.schema_from_types(word=str, qty=int)
    t = pw.io.s3.read("pail/fmt", aws_s3_settings=settings, format="csv",
                      schema=schema, mode="static")
    rows = sorted(pw.debug.table_to_pandas(t).itertuples(index=False))
    assert [(r.word, r.qty) for r in rows] == [
        ("alpha", 3), ("beta", 4), ("gamma", 5)]

    G.clear()
    c.put_object("jl/x.jsonl", b'{"word": "a", "qty": 1}\n'
                               b'{"word": "b", "qty": 2}\n')
    t2 = pw.io.s3.read("pail/jl", aws_s3_settings=settings,
                       format="jsonlines", schema=schema, mode="static",
                       with_metadata=True)
    df = pw.debug.table_to_pandas(t2)
    assert sorted(zip(df.word, df.qty)) == [("a", 1), ("b", 2)]
    assert all(m.value["path"].endswith("x.jsonl") for m in df._metadata)

    G.clear()
    t3 = pw.io.s3.read("pail/fmt", aws_s3_settings=settings,
                       format="plaintext", mode="static")
    lines = sorted(pw.debug.table_to_pandas(t3).data)
    assert "alpha,3" in lines and "word,qty" in lines
