"""Deliberately misordered locks — the CI canary proving the PWT2xx gate
bites.

``python -m pathway_tpu check --concurrency
tests/concurrency_negative_example.py`` must exit nonzero: ``ingest``
acquires ``_ingest_lock`` then ``_query_lock`` while ``query`` acquires
them in the opposite order — a lock-order inversion (PWT201). An ingest
thread and a query thread taking the two paths concurrently deadlock.
The module is never imported by the suite (the checker parses, it does
not execute).
"""

import threading


class MisorderedServingTier:
    def __init__(self):
        self._ingest_lock = threading.Lock()
        self._query_lock = threading.Lock()
        self.rows = []
        self.results = []

    def ingest(self, batch):
        with self._ingest_lock:
            with self._query_lock:
                self.rows.extend(batch)

    def query(self, q):
        with self._query_lock:
            with self._ingest_lock:
                self.results.append((q, len(self.rows)))
