"""REST endpoint input formats (reference: io/http/_server.py:50,525-535).

``custom`` parses the JSON body ({} on parse failure — required-field
validation then 400s) and merges URL query params; ``raw`` takes the
whole body as the ``query`` column. Pinned at the webserver dispatch
level with echo handlers.
"""

import json
import urllib.request

import pytest

from pathway_tpu.io.http import PathwayWebserver, rest_connector


def _post(url: str, body: bytes):
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture(scope="module")
def server():
    ws = PathwayWebserver(host="127.0.0.1", port=18591,
                          with_schema_endpoint=True)

    async def echo(payload):
        return {"got": payload}

    ws.register("/custom", ("POST",), echo, None, format="custom")
    ws.register("/raw", ("POST", "GET"), echo, None, format="raw")
    ws.start()
    return "http://127.0.0.1:18591"


def test_custom_format_parses_json_and_merges_params(server):
    code, body = _post(server + "/custom?extra=1", b'{"query": "hi"}')
    assert code == 200
    assert json.loads(body)["got"] == {"query": "hi", "extra": "1"}


def test_custom_format_unparseable_body_yields_empty_payload(server):
    # the reference's custom semantics: bad JSON -> {} (required-field
    # validation in RestSource then answers 400, not a silent wrap)
    code, body = _post(server + "/custom", b"not json at all")
    assert code == 200
    assert json.loads(body)["got"] == {}


def test_raw_format_takes_body_as_query(server):
    code, body = _post(server + "/raw", b"plain text question")
    assert code == 200
    assert json.loads(body)["got"] == {"query": "plain text question"}


def test_raw_format_applies_to_every_method(server):
    # GET has no body: raw semantics still hold and yield {'query': ''},
    # not the query-param dict custom would build
    with urllib.request.urlopen(server + "/raw?ignored=1", timeout=10) as r:
        assert r.status == 200
        assert json.loads(r.read())["got"] == {"query": ""}


def test_formats_are_keyed_per_method(server):
    # the same route can serve raw POSTs and a custom GET side by side
    ws = PathwayWebserver(host="127.0.0.1", port=18595)

    async def echo(payload):
        return {"got": payload}

    ws.register("/mixed", ("POST",), echo, None, format="raw")
    ws.register("/mixed", ("GET",), echo, None, format="custom")
    ws.start()
    base = "http://127.0.0.1:18595"
    code, body = _post(base + "/mixed", b"plain text")
    assert (code, json.loads(body)["got"]) == (200, {"query": "plain text"})
    with urllib.request.urlopen(base + "/mixed?q=1", timeout=10) as r:
        assert json.loads(r.read())["got"] == {"q": "1"}


def test_conflicting_format_reregistration_is_rejected():
    ws = PathwayWebserver(host="127.0.0.1", port=18596)

    async def echo(payload):
        return {"got": payload}

    ws.register("/r", ("POST",), echo, None, format="raw")
    with pytest.raises(ValueError, match="already registered"):
        ws.register("/r", ("POST",), echo, None, format="custom")
    # same-format re-registration stays allowed (handler swap)
    ws.register("/r", ("POST",), echo, None, format="raw")


def test_rejected_reregistration_is_atomic():
    ws = PathwayWebserver(host="127.0.0.1", port=18597)

    async def h1(payload):
        return {"h": 1}

    async def h2(payload):
        return {"h": 2}

    ws.register("/r", ("POST",), h1, None, format="raw")
    # GET would be new, POST conflicts: the whole call must be a no-op,
    # not leave GET /r registered with the new handler/format
    with pytest.raises(ValueError, match="already registered"):
        ws.register("/r", ("GET", "POST"), h2, None, format="custom")
    assert ("GET", "/r") not in ws._routes
    assert ("GET", "/r") not in ws._formats
    assert ws._routes[("POST", "/r")] is h1
    assert ws._formats[("POST", "/r")] == "raw"


def test_schema_endpoint_yaml_default_and_json(server):
    import urllib.request

    with urllib.request.urlopen(server + "/_schema", timeout=10) as r:
        assert r.headers.get_content_type() == "text/x-yaml"
        assert "openapi" in r.read().decode()
    with urllib.request.urlopen(server + "/_schema?format=json",
                                timeout=10) as r:
        assert json.loads(r.read())["openapi"]
    req = urllib.request.Request(server + "/_schema?format=xml")
    try:
        urllib.request.urlopen(req, timeout=10)
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_cors_headers_and_preflight():
    import urllib.request

    ws = PathwayWebserver(host="127.0.0.1", port=18594, with_cors=True)

    async def echo(payload):
        return {"ok": True}

    ws.register("/c", ("POST",), echo, None)
    ws.start()
    base = "http://127.0.0.1:18594"
    req = urllib.request.Request(base + "/c", method="OPTIONS")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 204
        assert r.headers["Access-Control-Allow-Origin"] == "*"
    code_body = _post(base + "/c", b"{}")
    assert code_body[0] == 200
    req = urllib.request.Request(base + "/c", data=b"{}", method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.headers["Access-Control-Allow-Origin"] == "*"


def test_rest_connector_infers_format_from_schema():
    import pathway_tpu.internals.schema as sch
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    # schemaless endpoint: raw inferred, so a plain-text POST becomes
    # {'query': body} (reference _server.py:733-736)
    ws = PathwayWebserver(host="127.0.0.1", port=18597)
    table, _ = rest_connector(webserver=ws, route="/infer")
    assert table._plan.params["datasource"].format == "raw"
    assert table.column_names() == ["query"]
    # schema-ful endpoint: custom inferred
    ws2 = PathwayWebserver(host="127.0.0.1", port=18598)
    table2, _ = rest_connector(webserver=ws2, route="/infer",
                               schema=sch.schema_from_types(question=str))
    assert table2._plan.params["datasource"].format == "custom"
    G.clear()


def test_rest_connector_validates_format_and_raw_schema():
    import pathway_tpu.internals.schema as sch
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    with pytest.raises(ValueError, match="unknown endpoint input format"):
        rest_connector(webserver=PathwayWebserver(port=18592),
                       schema=sch.schema_from_types(query=str),
                       format="yaml")
    with pytest.raises(ValueError, match="requires a 'query' column"):
        rest_connector(webserver=PathwayWebserver(port=18593),
                       schema=sch.schema_from_types(text=str),
                       format="raw")
    G.clear()
