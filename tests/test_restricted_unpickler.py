"""Restricted unpickler (persistence._safe_loads) vs everything the
snapshot writer actually emits: every reducer state_dict (all 18
REDUCER_FACTORIES), operator snapshot payloads (arrange rows keyed by
Pointer, dedup emitted maps, temporal watermark/stamp state), paged-store
page-table views, and the wire-format value types (numpy arrays, pandas
timestamps). The flip side: a payload referencing any global OUTSIDE the
whitelist is rejected by name, never constructed."""

from __future__ import annotations

import pickle

import numpy as np
import pytest

from pathway_tpu.engine.persistence import _safe_loads
from pathway_tpu.engine.reducers import (REDUCER_FACTORIES,
                                         make_reducer_state)
from pathway_tpu.internals.keys import Pointer


def _round_trip(value):
    return _safe_loads(pickle.dumps(value,
                                    protocol=pickle.HIGHEST_PROTOCOL))


# ---------------------------------------------------------------------------
# reducer state_dicts — every factory the engine registers
# ---------------------------------------------------------------------------

# representative add() feeds per reducer family (args, diff)
_FEEDS = {
    "count": [((), 1), ((), 1)],
    "sum": [((3,), 1), ((4,), 1)],
    "int_sum": [((3,), 1), ((4,), 1)],
    "float_sum": [((1.5,), 1), ((2.25,), 1)],
    "array_sum": [((np.array([1.0, 2.0]),), 1),
                  ((np.array([0.5, 0.5]),), 1)],
    "avg": [((3,), 1), ((5,), 1)],
    "min": [((3,), 1), ((7,), 1)],
    "max": [((3,), 1), ((7,), 1)],
    "argmin": [((3, "x"), 1), ((7, "y"), 1)],
    "argmax": [((3, "x"), 1), ((7, "y"), 1)],
    "unique": [(("u",), 1), (("u",), 1)],
    "any": [(("z",), 1)],
    "sorted_tuple": [((3,), 1), ((1,), 1)],
    "tuple": [((3, 0), 1), ((1, 1), 1)],
    "ndarray": [((1.0, 0), 1), ((2.0, 1), 1)],
    "earliest": [(("a", 1), 1), (("b", 2), 1)],
    "latest": [(("a", 1), 1), (("b", 2), 1)],
    "stateful": [(("r",), 1), (("s",), 1)],
}

# callables are re-supplied at construction, never serialized
_CTOR_KWARGS = {
    "stateful": {"fn": lambda st, rows: (st or 0) + len(rows)},
}


def _emit_equal(name, a, b) -> bool:
    if name in ("array_sum", "ndarray"):
        return np.array_equal(a, b)
    return a == b


@pytest.mark.parametrize("name", sorted(REDUCER_FACTORIES))
def test_every_reducer_state_dict_survives_safe_loads(name):
    assert name in _FEEDS, f"no feed defined for reducer {name!r}"
    kwargs = _CTOR_KWARGS.get(name, {})
    st = make_reducer_state(name, **kwargs)
    for args, diff in _FEEDS[name]:
        st.add(args, diff)
    state = _round_trip(st.state_dict())  # the exact persisted payload
    fresh = make_reducer_state(name, **kwargs)
    fresh.load_state(state)
    assert _emit_equal(name, fresh.emit(), st.emit())


def test_feed_table_covers_all_factories():
    # a reducer added without a feed here would silently skip coverage
    assert set(_FEEDS) == set(REDUCER_FACTORIES)


def test_multiset_rekey_survives_retraction_after_load():
    # load_state re-keys hash()-fingerprinted entries (the runtime twin
    # of PWT303): a post-restore retraction must find its entry
    st = make_reducer_state("min")
    st.add(("a",), 1)
    st.add(("b",), 1)
    fresh = make_reducer_state("min")
    fresh.load_state(_round_trip(st.state_dict()))
    fresh.add(("a",), -1)
    assert fresh.emit() == "b"


# ---------------------------------------------------------------------------
# operator snapshot payload shapes
# ---------------------------------------------------------------------------

def test_arrange_rows_with_pointer_keys_load():
    # StatefulArrangeOperator.snapshot_state: {"rows": {Pointer: tuple}}
    rows = {Pointer(7): ("a", 1), Pointer(9): ("b", 2)}
    assert _round_trip({"rows": rows}) == {"rows": rows}


def test_dedup_emitted_map_loads():
    # DeduplicateOperator.snapshot_state: {"emitted": {key: (row, c)}}
    payload = {"emitted": {Pointer(3): (("x", 1.5), 2)}}
    assert _round_trip(payload) == payload


def test_temporal_watermark_state_loads():
    # temporal/earliest-latest style state: watermark ticks plus
    # per-value stamp lists (plain ints/lists under fingerprint keys)
    payload = {"wm": 12,
               "stamps": {-123456789: [1, 4, 6]},
               "values": {-123456789: "a"}}
    assert _round_trip(payload) == payload


def test_paged_store_page_table_view_loads():
    # host-side page-table shape: logical slot -> (page, offset), plus
    # the side columns a paged snapshot would carry (codes, scales)
    payload = {
        "page_rows": 128,
        "slots": {i: (i // 128, i % 128) for i in range(0, 512, 64)},
        "codes": np.arange(8, dtype=np.int8),
        "scales": np.ones(8, dtype=np.float32),
    }
    out = _round_trip(payload)
    assert out["slots"] == payload["slots"]
    assert np.array_equal(out["codes"], payload["codes"])
    assert np.array_equal(out["scales"], payload["scales"])


def test_pandas_timestamp_values_load():
    import pandas as pd

    payload = {"t": pd.Timestamp("2026-08-06T12:00:00"),
               "dt": pd.Timedelta(seconds=90)}
    assert _round_trip(payload) == payload


# ---------------------------------------------------------------------------
# rejection — novel globals are refused by name
# ---------------------------------------------------------------------------

class _NotWhitelisted:
    pass


def test_novel_global_is_rejected_by_name():
    blob = pickle.dumps({"x": _NotWhitelisted()},
                        protocol=pickle.HIGHEST_PROTOCOL)
    with pytest.raises(pickle.UnpicklingError) as e:
        _safe_loads(blob)
    assert "_NotWhitelisted" in str(e.value)
    assert "forbidden" in str(e.value)


def test_os_system_reduce_payload_is_rejected():
    class _Evil:
        def __reduce__(self):
            import os
            return (os.system, ("true",))

    blob = pickle.dumps(_Evil(), protocol=pickle.HIGHEST_PROTOCOL)
    with pytest.raises(pickle.UnpicklingError):
        _safe_loads(blob)
