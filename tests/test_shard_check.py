"""Sharding/placement analyzer (static_check/shard_check.py): one
true-positive and one true-negative per PWT101–PWT110 code, the UDF
classifier, the iterate integration, and the CLI's ``--tpu-mesh`` /
``--json`` front door."""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import pathway_tpu as pw
import pathway_tpu.internals.schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.static_check import (MeshSpec, Severity,
                                                classify_udf,
                                                parse_mesh_spec)
from pathway_tpu.internals.static_check.shard_check import (
    check_attention_sharding,
    check_mesh_fits,
    check_pipeline_layout,
    check_shard_specs,
    check_sharded_dim,
)
from tests.utils import T


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


def codes(diags):
    return [d.code for d in diags]


def _streaming_table(tmp_path, **types):
    types = types or {"a": int}
    return pw.io.fs.read(str(tmp_path), format="json", mode="streaming",
                         schema=sch.schema_from_types(**types))


def _bind(table):
    pw.io.subscribe(table, lambda *a, **k: None)


def _knn_pipeline(tmp_path, *, mesh="auto", reserved_space=1024,
                  embedder=None, dimensions=16, dtype="float32",
                  tenant_quotas=None):
    """Streaming docs -> sharded KNN index -> bound query results."""
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index)

    docs = _streaming_table(tmp_path, doc=str)
    data = docs.select(vec=pw.apply_with_type(
        lambda d: np.zeros(16, dtype=np.float32), np.ndarray, docs.doc))
    index = default_brute_force_knn_document_index(
        data.vec, data, dimensions=dimensions, reserved_space=reserved_space,
        mesh=mesh, embedder=embedder, dtype=dtype,
        tenant_quotas=tenant_quotas)
    hits = index.query_as_of_now(data.vec, number_of_matches=1)
    _bind(hits)
    return hits


# ---------------------------------------------------------------------------
# mesh spec parsing
# ---------------------------------------------------------------------------

def test_parse_mesh_spec_forms():
    assert parse_mesh_spec("4x2") == MeshSpec(4, 2)
    assert parse_mesh_spec("4×2") == MeshSpec(4, 2)
    assert parse_mesh_spec("8") == MeshSpec(8, 1)
    assert parse_mesh_spec(None) is None
    assert parse_mesh_spec(MeshSpec(2, 2)) == MeshSpec(2, 2)
    from pathway_tpu.parallel.mesh import MeshConfig, make_mesh

    assert parse_mesh_spec(MeshConfig(data=4, model=2)) == MeshSpec(4, 2)
    assert parse_mesh_spec(make_mesh(MeshConfig(2, 1))) == MeshSpec(2, 1)
    with pytest.raises(ValueError, match="mesh spec"):
        parse_mesh_spec("4xbanana")


# ---------------------------------------------------------------------------
# PWT101 — mesh axes do not fit the device count
# ---------------------------------------------------------------------------

def test_pwt101_oversubscribed_mesh_is_error():
    diags = check_mesh_fits(3, 2, 4)
    assert codes(diags) == ["PWT101"]
    assert diags[0].is_error


def test_pwt101_non_dividing_mesh_is_error():
    # same severity as the runtime: MeshConfig.from_env refuses to build
    # this topology, so the checker must not wave it through
    diags = check_mesh_fits(3, 2, 8)
    assert codes(diags) == ["PWT101"]
    assert diags[0].is_error


def test_pwt101_malformed_mesh_value_is_a_diagnostic_not_a_crash(tmp_path):
    # a typo'd PATHWAY_STATIC_CHECK_MESH must not abort a warn-mode run
    t = _streaming_table(tmp_path)
    _bind(t.select(b=t.a * 2))
    diags = pw.static_check(mesh="4,2")
    assert "PWT101" in codes(diags)
    [d] = [d for d in diags if d.code == "PWT101"]
    assert "mesh spec" in d.message


def test_pwt101_negative_fitting_meshes():
    assert check_mesh_fits(4, 2, 8) == []
    assert check_mesh_fits(4, 1, 8) == []  # dividing submesh is fine


def test_pwt101_env_override_vs_analysis_mesh(tmp_path, monkeypatch):
    t = _streaming_table(tmp_path)
    _bind(t.select(b=t.a * 2))
    monkeypatch.setenv("PATHWAY_DATA_PARALLEL", "3")
    diags = pw.static_check(mesh="4x2")
    assert "PWT101" in codes(diags)
    monkeypatch.delenv("PATHWAY_DATA_PARALLEL")
    assert "PWT101" not in codes(pw.static_check(mesh="4x2"))


# ---------------------------------------------------------------------------
# PWT102 — sharded leading dim not divisible by the axis
# ---------------------------------------------------------------------------

def test_pwt102_non_divisible_knn_reservation(tmp_path):
    _knn_pipeline(tmp_path, reserved_space=1001)
    diags = pw.static_check(mesh="8x1")
    pwt102 = [d for d in diags if d.code == "PWT102"]
    assert len(pwt102) == 1 and pwt102[0].is_error
    assert "1001" in pwt102[0].message
    assert "rows/shard" in pwt102[0].message  # layout-accurate padding info


def test_pwt102_negative_divisible_reservation(tmp_path):
    _knn_pipeline(tmp_path, reserved_space=1024)
    assert "PWT102" not in codes(pw.static_check(mesh="8x1"))


def test_pwt102_pure_helpers():
    assert codes(check_sharded_dim(30, 8, what="x")) == ["PWT102"]
    assert check_sharded_dim(32, 8, what="x") == []
    assert check_sharded_dim(None, 8, what="x") == []
    assert codes(check_pipeline_layout(10, 4)) == ["PWT102"]
    assert check_pipeline_layout(12, 4) == []


# ---------------------------------------------------------------------------
# PWT103 — shard_map specs vs operand ranks / mesh axes
# ---------------------------------------------------------------------------

def test_pwt103_spec_longer_than_operand_rank():
    diags = check_shard_specs({"data": 8}, [("data", None, "data")], [2])
    assert codes(diags) == ["PWT103"]
    assert diags[0].is_error


def test_pwt103_spec_names_unknown_axis():
    diags = check_shard_specs({"data": 8, "model": 1}, [("tensor",)], [3])
    assert codes(diags) == ["PWT103"]
    assert "tensor" in diags[0].message


def test_pwt103_negative_kernel_layout_is_consistent(tmp_path):
    # the sharded-KNN search kernel's own spec/rank contract (propagated
    # from the plan's factory dtype into the kernel wrapper layout) must
    # be clean for every slab dtype
    from pathway_tpu.parallel.sharded_knn import search_operand_layout

    for dtype in ("float32", "bfloat16", "int8"):
        layout = search_operand_layout(dtype)
        assert check_shard_specs(
            {"data": 8, "model": 1},
            [spec for spec, _ in layout],
            [rank for _, rank in layout]) == []
    _knn_pipeline(tmp_path, dtype="int8")
    assert "PWT103" not in codes(pw.static_check(mesh="8x1"))


def test_shard_map_rejects_unknown_axis_eagerly():
    from jax.sharding import PartitionSpec as P

    from pathway_tpu.parallel.mesh import MeshConfig, make_mesh, shard_map

    mesh = make_mesh(MeshConfig(2, 1))
    with pytest.raises(ValueError, match="PWT103"):
        shard_map(lambda x: x, mesh=mesh, in_specs=(P("bogus"),),
                  out_specs=P())


# ---------------------------------------------------------------------------
# PWT104 — slab pinned to a different topology than the pipeline
# ---------------------------------------------------------------------------

def test_pwt104_index_mesh_differs_from_analysis_mesh(tmp_path):
    from pathway_tpu.parallel.mesh import MeshConfig, make_mesh

    _knn_pipeline(tmp_path, mesh=make_mesh(MeshConfig(2, 1)))
    diags = pw.static_check(mesh="8x1")
    pwt104 = [d for d in diags if d.code == "PWT104"]
    assert len(pwt104) == 1
    assert pwt104[0].severity is Severity.WARNING


def test_pwt104_negative_auto_and_matching_meshes(tmp_path):
    from pathway_tpu.parallel.mesh import MeshConfig, make_mesh

    _knn_pipeline(tmp_path, mesh="auto")
    assert "PWT104" not in codes(pw.static_check(mesh="8x1"))
    G.clear()
    _knn_pipeline(tmp_path, mesh=make_mesh(MeshConfig(2, 1)))
    assert "PWT104" not in codes(pw.static_check(mesh="2x1"))


def test_pwt104_runtime_counterpart_warns(caplog):
    from pathway_tpu.engine.index_ops import ExternalIndexOperator
    from pathway_tpu.parallel.mesh import MeshConfig, make_mesh, use_mesh
    from pathway_tpu.parallel.sharded_knn import ShardedKnnIndex

    idx = ShardedKnnIndex(8, mesh=make_mesh(MeshConfig(2, 1)))
    with use_mesh(make_mesh(MeshConfig(8, 1))):
        with caplog.at_level("WARNING", logger="pathway_tpu.shard_check"):
            ExternalIndexOperator(
                index=idx, data_vec_pos=0, data_filter_pos=None,
                query_vec_pos=0, query_limit_pos=None,
                query_filter_pos=None)
    assert any("PWT104" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# PWT105 — host-device sync point on a per-batch path
# ---------------------------------------------------------------------------

def _syncy(x):
    return np.asarray(x).item() * 2.0


def test_pwt105_item_sync_on_streaming_path(tmp_path):
    t = _streaming_table(tmp_path)
    _bind(t.select(b=pw.apply(_syncy, t.a)))
    diags = pw.static_check()
    assert "PWT105" in codes(diags)
    [d] = [d for d in diags if d.code == "PWT105"]
    assert ".item()" in d.message


def test_pwt105_negative_static_pipeline_or_pure_udf(tmp_path):
    t = T("""
    a
    1
    """)
    assert "PWT105" not in codes(pw.static_check(t.select(
        b=pw.apply(_syncy, t.a))))
    G.clear()
    s = _streaming_table(tmp_path)
    _bind(s.select(b=s.a * 2))
    assert "PWT105" not in codes(pw.static_check())


# ---------------------------------------------------------------------------
# PWT106 — ulysses heads not divisible by the axis
# ---------------------------------------------------------------------------

def test_pwt106_heads_not_divisible():
    diags = check_attention_sharding((2, 32, 6, 8), "4x1", scheme="ulysses")
    assert codes(diags) == ["PWT106"]
    assert diags[0].is_error


def test_pwt106_negative_divisible_heads_or_ring():
    assert check_attention_sharding((2, 32, 8, 8), "4x1",
                                    scheme="ulysses") == []
    # ring attention never re-shards heads
    assert check_attention_sharding((2, 32, 6, 8), "4x1",
                                    scheme="ring") == []


def test_ulysses_runtime_error_mentions_code():
    import jax.numpy as jnp

    from pathway_tpu.parallel import MeshConfig, make_mesh, ulysses_attention

    mesh = make_mesh(MeshConfig(4, 1))
    q = jnp.zeros((1, 16, 6, 4))
    with pytest.raises(ValueError, match="PWT106"):
        ulysses_attention(q, q, q, mesh=mesh)


def test_ring_runtime_error_on_non_divisible_seq():
    import jax.numpy as jnp

    from pathway_tpu.parallel import MeshConfig, make_mesh, ring_attention

    mesh = make_mesh(MeshConfig(4, 1))
    q = jnp.zeros((1, 18, 4, 4))
    with pytest.raises(ValueError, match="PWT102"):
        ring_attention(q, q, q, mesh=mesh)


# ---------------------------------------------------------------------------
# PWT107 — model axis configured but unused
# ---------------------------------------------------------------------------

def test_pwt107_model_axis_unused(tmp_path):
    t = _streaming_table(tmp_path)
    _bind(t.select(b=t.a * 2))
    diags = pw.static_check(mesh="4x2")
    pwt107 = [d for d in diags if d.code == "PWT107"]
    assert len(pwt107) == 1
    assert pwt107[0].severity is Severity.INFO


def test_pwt107_negative_model_1_or_device_embedder(tmp_path):
    t = _streaming_table(tmp_path)
    _bind(t.select(b=t.a * 2))
    assert "PWT107" not in codes(pw.static_check(mesh="8x1"))
    G.clear()

    class DeviceEmbedder:
        def encode_batch_device(self, texts):  # model-parallel capable
            raise NotImplementedError

        def get_embedding_dimension(self):
            return 16

    _knn_pipeline(tmp_path, mesh=None, embedder=DeviceEmbedder())
    assert "PWT107" not in codes(pw.static_check(mesh="4x2"))


# ---------------------------------------------------------------------------
# PWT108 — fused donated slab with no reserved capacity
# ---------------------------------------------------------------------------

class _DeviceEmbedder:
    def encode_batch_device(self, texts):
        raise NotImplementedError

    def get_embedding_dimension(self):
        return 16


def test_pwt108_fused_ingest_without_reservation(tmp_path, monkeypatch):
    # the fused-path cliff only exists on the contiguous slab — the paged
    # store grows the fused path by allocating pages
    monkeypatch.setenv("PATHWAY_PAGED_STORE", "0")
    _knn_pipeline(tmp_path, mesh=None, embedder=_DeviceEmbedder(),
                  reserved_space=0)
    diags = pw.static_check()
    pwt108 = [d for d in diags if d.code == "PWT108"]
    assert len(pwt108) == 1
    assert pwt108[0].severity is Severity.WARNING
    assert "1024" in pwt108[0].message  # names the pinned minimum capacity


def test_pwt108_negative_reserved_or_unfused(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_PAGED_STORE", "0")
    _knn_pipeline(tmp_path, mesh=None, embedder=_DeviceEmbedder(),
                  reserved_space=4096)
    assert "PWT108" not in codes(pw.static_check())
    G.clear()
    # a plain UDF embedder has no fused device path to lose
    _knn_pipeline(tmp_path, mesh=None, reserved_space=0)
    assert "PWT108" not in codes(pw.static_check())


def test_pwt108_suppressed_under_paged_store(tmp_path, monkeypatch):
    # default (paged) storage: fused ingest grows by allocating a page,
    # so the unreserved-slab cliff PWT108 warns about does not exist
    monkeypatch.delenv("PATHWAY_PAGED_STORE", raising=False)
    _knn_pipeline(tmp_path, mesh=None, embedder=_DeviceEmbedder(),
                  reserved_space=0)
    assert "PWT108" not in codes(pw.static_check())


# ---------------------------------------------------------------------------
# PWT111 — paged-store reservation / tenant quota layout
# ---------------------------------------------------------------------------

def test_pwt111_unaligned_reservation(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_PAGED_STORE", raising=False)
    monkeypatch.delenv("PATHWAY_PAGE_ROWS", raising=False)
    _knn_pipeline(tmp_path, mesh=None, reserved_space=1500)
    diags = pw.static_check()
    pwt = [d for d in diags if d.code == "PWT111"]
    assert len(pwt) == 1
    assert pwt[0].severity is Severity.WARNING
    assert "1500" in pwt[0].message and "2048" in pwt[0].message


def test_pwt111_unaligned_tenant_quota(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_PAGED_STORE", raising=False)
    _knn_pipeline(tmp_path, mesh=None, reserved_space=1024,
                  tenant_quotas={"acme": 1500, "globex": 2048})
    diags = pw.static_check()
    pwt = [d for d in diags if d.code == "PWT111"]
    assert len(pwt) == 1  # only acme's quota is unaligned
    assert "acme" in pwt[0].message and "2048" in pwt[0].message


def test_pwt111_quotas_past_device_hbm(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_PAGED_STORE", raising=False)
    monkeypatch.setenv("PATHWAY_DEVICE_HBM_GB", "1")
    # 16 B/row f32 rows: 2^27 rows/tenant x 4 tenants = 8 GiB >> 1 GiB
    quotas = {f"t{i}": (1 << 27) for i in range(4)}
    _knn_pipeline(tmp_path, mesh=None, reserved_space=1024,
                  tenant_quotas=quotas)
    diags = pw.static_check()
    over = [d for d in diags if d.code == "PWT111" and d.is_error]
    assert len(over) == 1
    assert "HBM" in over[0].message


def test_pwt111_negative_cases(tmp_path, monkeypatch):
    # page-aligned reservation + aligned, HBM-fitting quotas: clean
    monkeypatch.delenv("PATHWAY_PAGED_STORE", raising=False)
    _knn_pipeline(tmp_path, mesh=None, reserved_space=2048,
                  tenant_quotas={"acme": 4096})
    assert "PWT111" not in codes(pw.static_check())
    G.clear()
    # slab mode: the paged layout rules do not apply
    monkeypatch.setenv("PATHWAY_PAGED_STORE", "0")
    _knn_pipeline(tmp_path, mesh=None, reserved_space=1500)
    assert "PWT111" not in codes(pw.static_check())


# ---------------------------------------------------------------------------
# PWT109 — host-only UDF on a streaming hot path
# ---------------------------------------------------------------------------

def _hosty(x):
    out = 0.0
    for tok in str(x).split(","):
        out += float(tok)
    return out


def test_pwt109_host_udf_on_streaming_path(tmp_path):
    t = _streaming_table(tmp_path)
    _bind(t.select(b=pw.apply(_hosty, t.a)))
    diags = pw.static_check()
    pwt109 = [d for d in diags if d.code == "PWT109"]
    assert len(pwt109) == 1
    assert pwt109[0].severity is Severity.WARNING
    assert "loop" in pwt109[0].message


def test_pwt109_negative_static_source_or_traceable_udf(tmp_path):
    t = T("""
    a
    1
    """)
    assert "PWT109" not in codes(pw.static_check(
        t.select(b=pw.apply(_hosty, t.a))))
    G.clear()
    s = _streaming_table(tmp_path)
    _bind(s.select(b=pw.apply(lambda x: x * 2, s.a)))
    assert "PWT109" not in codes(pw.static_check())


# ---------------------------------------------------------------------------
# PWT110 — traceable UDF dispatched row-by-row
# ---------------------------------------------------------------------------

def test_pwt110_traceable_udf_rowwise_on_streaming_path(tmp_path):
    t = _streaming_table(tmp_path)
    _bind(t.select(b=pw.apply(lambda x: x * 2 + 1, t.a)))
    diags = pw.static_check()
    pwt110 = [d for d in diags if d.code == "PWT110"]
    assert len(pwt110) == 1
    assert pwt110[0].severity is Severity.INFO
    assert "batch=True" in pwt110[0].message


def test_pwt110_negative_batch_udf_or_static_source(tmp_path):
    t = _streaming_table(tmp_path)
    doubler = pw.udf(lambda xs: [x * 2 for x in xs], batch=True,
                     deterministic=True)
    _bind(t.select(b=doubler(t.a)))
    assert "PWT110" not in codes(pw.static_check())
    G.clear()
    s = T("""
    a
    1
    """)
    assert "PWT110" not in codes(pw.static_check(
        s.select(b=pw.apply(lambda x: x * 2, s.a))))


def test_pwt110_wording_tracks_autojit_state(tmp_path, monkeypatch):
    """With auto-jit on (the default) PWT110 is informational — the
    runtime fuses the UDF, so the message must NOT send the user off to a
    manual batch=True rewrite; with PATHWAY_AUTO_JIT=0 the manual rewrite
    is the suggestion again."""
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    t = _streaming_table(tmp_path)
    _bind(t.select(b=pw.apply(lambda x: x * 2 + 1, t.a)))
    d, = [d for d in pw.static_check() if d.code == "PWT110"]
    assert "auto-jitted" in d.message
    assert "no change needed" in d.message
    G.clear()
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "0")
    t = _streaming_table(tmp_path)
    _bind(t.select(b=pw.apply(lambda x: x * 2 + 1, t.a)))
    d, = [d for d in pw.static_check() if d.code == "PWT110"]
    assert "auto-jitted" not in d.message
    assert "fix: pw.udf(batch=True)" in d.message
    G.clear()
    # a body the fused tier will refuse (math.exp has no IEEE-exact
    # vector counterpart) must keep the actionable manual advice even
    # with auto-jit on — "will be auto-jitted" would be an overclaim
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    t = _streaming_table(tmp_path)
    _bind(t.select(b=pw.apply(lambda y: math.exp(y), t.a)))
    d, = [d for d in pw.static_check() if d.code == "PWT110"]
    assert "auto-jitted" not in d.message
    assert "fix: pw.udf(batch=True)" in d.message


def test_pwt109_wording_gains_overlap_caveat(tmp_path, monkeypatch):
    """Host-only-on-hot-path keeps its warning either way, but with
    auto-jit on it names the WindVE-style host/device overlap the split
    lowering provides."""
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    t = _streaming_table(tmp_path)
    _bind(t.select(b=pw.apply(_hosty, t.a)))
    d, = [d for d in pw.static_check() if d.code == "PWT109"]
    assert "overlapped with the device leg" in d.message
    G.clear()
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "0")
    t = _streaming_table(tmp_path)
    _bind(t.select(b=pw.apply(_hosty, t.a)))
    d, = [d for d in pw.static_check() if d.code == "PWT109"]
    assert "overlapped" not in d.message


# ---------------------------------------------------------------------------
# UDF classifier
# ---------------------------------------------------------------------------

def test_classifier_traceable_vmappable_host():
    assert classify_udf(lambda x: x * 2 + 1).kind == "traceable"
    branchy = classify_udf(lambda x: x * 2 if x > 0 else -x)
    assert branchy.kind == "vmappable"
    assert classify_udf(_hosty).kind == "host"
    sync = classify_udf(_syncy)
    assert sync.jit_eligible and sync.sync_points


def test_classifier_async_and_sourceless():
    async def aget(x):
        return x

    assert classify_udf(aget).kind == "host"
    # builtins have no source or bytecode: conservative host
    assert classify_udf(len).kind == "host"


def test_classifier_bytecode_fallback_sees_control_flow():
    # a pure-local loop has an empty co_names: the bytecode fallback must
    # still classify it host (FOR_ITER/jumps), never traceable
    ns: dict = {}
    exec(textwrap.dedent("""
        def loopy(xs):
            t = 0
            for v in xs:
                t += v * v
            return t

        def straight(x):
            return x * 2 + 1
    """), ns)
    assert classify_udf(ns["loopy"]).kind == "host"
    assert classify_udf(ns["straight"]).kind == "traceable"


def test_classification_is_recorded_for_run_py(tmp_path):
    # the hook run.py will use to auto-jit the traceable class: the
    # analyzer stamps _shard_class on the plan's apply expressions and
    # aggregates them by function name
    from pathway_tpu.internals import expression as ex
    from pathway_tpu.internals.static_check import Analyzer

    t = _streaming_table(tmp_path)
    out = t.select(b=pw.apply(lambda x: x * 2, t.a))
    _bind(out)
    analyzer = Analyzer()
    analyzer.run()
    # keys carry the definition site so two lambdas never collide
    lambdas = {k: c for k, c in analyzer.udf_classifications.items()
               if k.startswith("<") or "<lambda>" in k}
    assert lambdas and any("test_shard_check.py" in k for k in lambdas)
    assert all(c.kind == "traceable" for c in lambdas.values())
    stamped = [
        sub
        for node in analyzer._nodes.values()
        for e in node.exprs
        for sub in ex.walk(e)
        if isinstance(sub, ex.ApplyExpression)
        and getattr(sub, "_shard_class", None) is not None
    ]
    assert stamped and all(s._shard_class.kind == "traceable"
                           for s in stamped)


# ---------------------------------------------------------------------------
# pw.iterate integration
# ---------------------------------------------------------------------------

def test_iterate_deep_body_does_not_hit_recursion_limit():
    t = T("""
    a
    1
    """)

    def body(t):
        for _ in range(1200):
            t = t.select(a=pw.this.a)
        return t

    result = pw.iterate(body, t=t)
    assert pw.static_check(result) == []


def test_iterate_body_codes_not_double_reported(tmp_path):
    # the body executes once per iteration at runtime, but the analyzer
    # sees ONE body graph: a diagnostic inside it must appear exactly once
    s = _streaming_table(tmp_path)

    def body(t):
        return t.select(a=pw.apply_with_type(_hosty, float, t.a))

    result = pw.iterate(body, t=s)
    _bind(result)
    diags = pw.static_check()
    assert codes(diags).count("PWT109") == 1


def test_iterate_body_dtype_errors_are_found():
    t = T("""
    a | b
    1 | x
    """)

    def body(t):
        return t.select(a=t.a + 1, b=t.b)

    bad = pw.iterate(body, t=t.select(a=t.a, b=t.b))
    # seed a dtype error inside the body of a second iterate
    def bad_body(t):
        return t.select(a=t.a + t.b, b=t.b)

    worse = pw.iterate(bad_body, t=t)
    diags = pw.static_check(bad, worse)
    assert codes(diags).count("PWT001") == 1


# ---------------------------------------------------------------------------
# MeshConfig.from_env eager validation (parallel/mesh.py)
# ---------------------------------------------------------------------------

def test_from_env_rejects_oversubscription(monkeypatch):
    from pathway_tpu.parallel.mesh import MeshConfig

    monkeypatch.setenv("PATHWAY_DATA_PARALLEL", "5")
    monkeypatch.setenv("PATHWAY_MODEL_PARALLEL", "2")
    with pytest.raises(ValueError) as e:
        MeshConfig.from_env(8)
    assert "PATHWAY_DATA_PARALLEL" in str(e.value)
    assert "PATHWAY_MODEL_PARALLEL" in str(e.value)


def test_from_env_rejects_non_dividing_product(monkeypatch):
    from pathway_tpu.parallel.mesh import MeshConfig

    monkeypatch.setenv("PATHWAY_DATA_PARALLEL", "3")
    monkeypatch.delenv("PATHWAY_MODEL_PARALLEL", raising=False)
    with pytest.raises(ValueError, match="does not divide"):
        MeshConfig.from_env(8)


def test_from_env_rejects_non_integer(monkeypatch):
    from pathway_tpu.parallel.mesh import MeshConfig

    monkeypatch.setenv("PATHWAY_DATA_PARALLEL", "lots")
    with pytest.raises(ValueError, match="positive integers"):
        MeshConfig.from_env(8)


def test_from_env_accepts_valid_and_default(monkeypatch):
    from pathway_tpu.parallel.mesh import MeshConfig

    monkeypatch.setenv("PATHWAY_DATA_PARALLEL", "4")
    monkeypatch.setenv("PATHWAY_MODEL_PARALLEL", "2")
    assert MeshConfig.from_env(8) == MeshConfig(4, 2)
    monkeypatch.delenv("PATHWAY_DATA_PARALLEL")
    monkeypatch.delenv("PATHWAY_MODEL_PARALLEL")
    assert MeshConfig.from_env(8) == MeshConfig(8, 1)


# ---------------------------------------------------------------------------
# CLI: --tpu-mesh / --json
# ---------------------------------------------------------------------------

def _run_check(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu", "check", *args],
        capture_output=True, text=True, env=env, timeout=300,
        cwd="/root/repo")


NEGATIVE_EXAMPLE = os.path.join(
    os.path.dirname(__file__), "shard_check_negative_example.py")


def test_cli_tpu_mesh_flags_seeded_bad_slab():
    proc = _run_check("--tpu-mesh", "8x1", NEGATIVE_EXAMPLE)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "PWT102" in proc.stdout


def test_cli_tpu_mesh_json_output():
    proc = _run_check("--tpu-mesh", "8x1", "--json", NEGATIVE_EXAMPLE)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    entries = json.loads(proc.stdout)
    pwt102 = [e for e in entries if e["code"] == "PWT102"]
    assert pwt102 and pwt102[0]["severity"] == "error"
    assert pwt102[0]["file"].endswith("shard_check_negative_example.py")
    assert isinstance(pwt102[0]["line"], int)
    assert pwt102[0]["script"].endswith("shard_check_negative_example.py")


def test_cli_without_mesh_passes_the_fixture():
    # the seeded misconfiguration is mesh-relative: without a topology the
    # slab stays unsharded and the script is clean of errors
    proc = _run_check(NEGATIVE_EXAMPLE)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_rejects_malformed_mesh(tmp_path):
    script = tmp_path / "empty.py"
    script.write_text("")
    proc = _run_check("--tpu-mesh", "4xbanana", str(script))
    assert proc.returncode != 0
    assert "mesh spec" in proc.stderr


def test_cli_json_clean_script_emits_empty_list(tmp_path):
    script = tmp_path / "clean.py"
    script.write_text(textwrap.dedent("""
        import pathway_tpu as pw
        t = pw.debug.table_from_markdown('''
        a
        1
        ''')
        pw.debug.compute_and_print(t.select(c=t.a * 2))
    """))
    proc = _run_check("--json", str(script))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []
