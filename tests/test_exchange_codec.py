"""Property/fuzz coverage of the columnar exchange wire format
(engine/wire.py): decode(encode(x)) == x over randomized Value payloads —
every scalar type, mixed-type columns, nullable columns, empty lists,
dict-nested payloads, the wm/bcast side-channels — plus the explicit
fallback edges (ragged rows, exotic cells, int64 overflow, surrogates).

The N-worker-vs-1-worker byte-identity runs over both transports live in
tests/test_sharded.py (subprocess clusters); this file owns the codec.
"""

from __future__ import annotations

import math
import random
import string

import numpy as np
import pytest

from pathway_tpu.engine import wire
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import Pointer, hash_values


def _eq(a, b) -> bool:
    """Structural equality tolerant of NaN and ndarray cells."""
    if type(a) is not type(b):
        # bool/int/float cross-type equality must NOT pass (1 != True on
        # the wire: the codec is type-preserving)
        return False
    if isinstance(a, float):
        return (math.isnan(a) and math.isnan(b)) or a == b
    if isinstance(a, np.ndarray):
        return a.dtype == b.dtype and np.array_equal(a, b, equal_nan=True)
    if isinstance(a, dict):
        return a.keys() == b.keys() and all(_eq(v, b[k])
                                            for k, v in a.items())
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(map(_eq, a, b))
    return a == b


def _roundtrip(tag, payload):
    chunks, total, n_enc = wire.encode_frame(tag, payload)
    blob = b"".join(chunks)
    assert total == len(blob)
    rtag, out, n_dec = wire.decode_frame(blob)
    assert _eq(rtag, tag)
    assert n_enc == n_dec
    assert _eq(out, payload), (payload, out)
    return out, n_enc


_SCALAR_POOLS = [
    lambda rng: rng.randrange(-2**40, 2**40),
    lambda rng: rng.randrange(-2**80, 2**80),          # past int64
    lambda rng: rng.random() * 1e6 - 5e5,
    lambda rng: rng.choice([float("nan"), float("inf"), -0.0, 1e-300]),
    lambda rng: "".join(rng.choices(string.printable, k=rng.randrange(12))),
    lambda rng: rng.choice(["", "héllo wörld", "日本語", "a" * 100]),
    lambda rng: rng.choice([True, False]),
    lambda rng: None,
    lambda rng: Pointer(rng.randrange(2**128)),
    lambda rng: bytes(rng.randrange(256) for _ in range(rng.randrange(8))),
    lambda rng: tuple(rng.randrange(9) for _ in range(rng.randrange(3))),
    lambda rng: np.arange(rng.randrange(1, 5), dtype=np.float32),
    lambda rng: Json({"k": rng.randrange(9)}),
]


def _rand_value(rng):
    return rng.choice(_SCALAR_POOLS)(rng)


def _rand_entries(rng, uniform_prob=0.5):
    n = rng.choice([1, 2, 3, 17, 100])
    width = rng.randrange(5)
    if rng.random() < uniform_prob:
        # homogeneous columns — the typed fast paths (incl. nullable)
        makers = [rng.choice(_SCALAR_POOLS) for _ in range(width)]
        nullable = [rng.random() < 0.3 for _ in range(width)]
        rows = [tuple(None if nullable[c] and rng.random() < 0.4
                      else makers[c](rng) for c in range(width))
                for _ in range(n)]
    else:
        # mixed-type columns — per-column pickle fallback
        rows = [tuple(_rand_value(rng) for _ in range(width))
                for _ in range(n)]
    return [(hash_values("fz", rng.randrange(10**9)), row,
             rng.choice([1, -1, 3, -2**40]))
            for row in rows]


def _rand_payload(rng, depth=0):
    shape = rng.randrange(6 if depth < 2 else 4)
    if shape == 0:
        return _rand_entries(rng)
    if shape == 1:
        return rng.choice([None, True, False, 7, "x", 3.5, [],
                           [1, 2, 3], ["not", "entries"]])
    if shape == 2:
        return {"rows": {rng.randrange(4): {rng.randrange(64):
                                            _rand_entries(rng)}},
                "wm": rng.choice([None, 17, 3.25, "2026-01-01"]),
                "bcast": rng.choice([None,
                                     {0: _rand_entries(rng)}])}
    if shape == 3:
        return _rand_value(rng)
    if shape == 4:
        return {rng.choice(["a", 5, True, None]): _rand_payload(rng,
                                                                depth + 1)
                for _ in range(rng.randrange(4))}
    return {i: _rand_payload(rng, depth + 1) for i in range(2)}


@pytest.mark.parametrize("seed", range(40))
def test_fuzz_roundtrip(seed):
    rng = random.Random(seed)
    payload = _rand_payload(rng)
    tag = rng.choice([("x", 3, 0), ("g", 1, 7), ("tick", 12), "s"])
    _roundtrip(tag, payload)


def test_row_accounting_counts_entries_not_side_channels():
    rng = random.Random(1234)
    ents = _rand_entries(rng)
    payload = {"rows": {0: {0: ents}}, "wm": 3, "bcast": {1: ents}}
    _out, n = _roundtrip(("x", 0, 0), payload)
    assert n == len(ents)  # bcast copies and wm excluded
    assert wire.payload_rows(payload) == len(ents)


def test_typed_column_fast_paths_take_columnar_kind(monkeypatch):
    ents = [(Pointer(i), (i, float(i), f"s{i}", i % 2 == 0, None,
                          Pointer(i * 3),
                          i if i % 2 else None,        # Optional[int]
                          float(i) if i % 3 else None,  # Optional[float]
                          f"t{i}" if i % 2 else None),  # Optional[str]
             1) for i in range(64)]
    payload = {"rows": {0: {0: ents}}, "wm": None, "bcast": None}
    # every column above has a typed fast path: the per-column pickle
    # fallback must never fire for this payload
    monkeypatch.setattr(
        wire, "_enc_col_pkl",
        lambda col, out: (_ for _ in ()).throw(
            AssertionError(f"pickle fallback hit for column {col[:3]}...")))
    chunks, _t, _n = wire.encode_frame(("x", 1, 0), payload)
    blob = b"".join(chunks)
    assert blob[3] == wire.KIND_COLUMNAR
    monkeypatch.undo()
    _roundtrip(("x", 1, 0), payload)


def test_type_preservation_across_lookalike_columns():
    """bool vs int, int vs float, -0.0, and Pointer vs int must come back
    as the exact types that went in (they compare equal but hash/route
    differently downstream)."""
    ents = [(Pointer(1), (True, 1, 1.0, -0.0, Pointer(5)), 1),
            (Pointer(2), (False, 0, 0.0, 0.25, Pointer(6)), 1)]
    out, _ = _roundtrip(("x", 0, 0), {"rows": {0: {0: ents}}})
    row0 = out["rows"][0][0][0][1]
    assert row0[0] is True and type(row0[1]) is int
    assert type(row0[2]) is float and row0[2] == 1.0
    assert math.copysign(1.0, row0[3]) == -1.0
    assert type(row0[4]) is Pointer


def test_ragged_and_non_tuple_rows_fall_back_losslessly():
    ents = [(Pointer(1), ("a", 1), 1),
            (Pointer(2), ("b", 2, "extra"), -1),     # ragged width
            (Pointer(3), "not-a-tuple", 1)]          # non-tuple row
    _roundtrip(("x", 0, 0), {"rows": {0: {0: ents}}})


def test_overlong_entry_tuples_are_not_truncated():
    """A list whose FIRST element looks like an entry but whose tail
    carries 4-tuples (or non-tuples) must ship via pickle, not silently
    drop the extra elements — the codec never loses data it does not
    understand."""
    mixed = [(Pointer(5), ("a", 1), 1),
             (Pointer(6), ("b", 2), 1, "EXTRA")]     # 4-tuple tail
    out, n = _roundtrip(("x", 0, 0), {"rows": {0: {0: mixed}}})
    assert out["rows"][0][0][1] == (Pointer(6), ("b", 2), 1, "EXTRA")
    mixed2 = [(Pointer(5), ("a", 1), 1), "stray"]    # non-tuple tail
    _roundtrip(("x", 0, 0), {"rows": {0: {0: mixed2}}})


def test_big_diffs_and_big_keys():
    ents = [(Pointer(2**128 - 1), ("x",), 2**50),
            (Pointer(0), ("y",), -2**50)]
    out, _ = _roundtrip(("x", 0, 0), {"rows": {0: {0: ents}}})
    got = out["rows"][0][0]
    assert got[0][2] == 2**50 and got[1][2] == -2**50
    assert int(got[0][0]) == 2**128 - 1


def test_surrogate_strings_fall_back_to_pickle_column():
    # lone surrogates cannot encode to utf-8; the column must ride pickle
    ents = [(Pointer(i), ("\ud800bad" if i else "fine",), 1)
            for i in range(3)]
    _roundtrip(("x", 0, 0), {"rows": {0: {0: ents}}})


def test_whole_frame_pickle_fallback(monkeypatch):
    """A columnar-encoder failure (future codec bug, exotic structure)
    must degrade to the kind-0 whole-frame pickle, not a send error —
    and the kind-0 path must still decode with correct row accounting."""
    def boom(*_a, **_k):
        raise RuntimeError("seeded codec failure")

    monkeypatch.setattr(wire, "_enc_node", boom)
    ents = [(Pointer(i), (i,), 1) for i in range(5)]
    payload = {"rows": {0: {0: ents}}, "wm": None, "bcast": None}
    chunks, _t, n = wire.encode_frame(("x", 0, 0), payload)
    blob = b"".join(chunks)
    assert blob[3] == wire.KIND_PICKLE
    tag, out, n_dec = wire.decode_frame(blob)
    assert tag == ("x", 0, 0)
    assert out == payload
    assert n == n_dec == 5


def test_gather_payload_shape():
    # the ("g", time, node) exchange ships {input_j: entries} or None
    ents = [(hash_values("g", i), (i, f"v{i}"), 1) for i in range(20)]
    _out, n = _roundtrip(("g", 4, 9), {0: ents, 2: ents[:3]})
    assert n == 23
    _roundtrip(("g", 4, 9), None)


def test_streaming_tick_payload_shape():
    ents = [(hash_values("t", i), (f"w{i}", i), 1) for i in range(10)]
    payload = {"rows": {0: ents}, "any": True, "closed": False}
    _out, n = _roundtrip(("tick", 31), payload)
    assert n == 10


def test_bad_frames_raise_named_errors():
    with pytest.raises(ValueError, match="magic"):
        wire.decode_frame(b"XX\x01\x01garbage")
    with pytest.raises(ValueError, match="version"):
        wire.decode_frame(wire.MAGIC + bytes([99, 0]) + b"x")


def test_empty_and_single_entry_lists():
    for ents in ([], [(Pointer(3), (), 1)]):
        payload = {"rows": {0: {0: ents}}, "wm": None, "bcast": None}
        _out, n = _roundtrip(("x", 0, 0), payload)
        assert n == len(ents)
