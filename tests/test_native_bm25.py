"""Native C++ BM25 engine vs the Python engine
(native/text_index.cpp; reference equivalent: TantivyIndex,
src/external_integration/tantivy_integration.rs)."""

import pytest

from pathway_tpu.internals.keys import hash_values
from pathway_tpu.ops.bm25 import BM25Index, NativeBM25Index, create_bm25_index

DOCS = {
    "d1": "systolic arrays multiply matrices in hardware",
    "d2": "streaming dataflow engines process incremental updates",
    "d3": "the tpu matrix unit is a systolic array",
    "d4": "hash joins shuffle rows between workers",
}


def _build(cls):
    idx = cls()
    keys = {}
    for name, text in DOCS.items():
        keys[name] = hash_values(name)
        idx.add(keys[name], text, filter_data={"name": name})
    return idx, keys


def test_native_builds_and_matches_python_ranking():
    try:
        native, nkeys = _build(NativeBM25Index)
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    python, pkeys = _build(BM25Index)
    assert len(native) == len(python) == 4

    for query in ("systolic array", "incremental updates", "rows workers",
                  "nothing matches this zz"):
        nres = native.search([(None, query, 4, None)])[0]
        pres = python.search([(None, query, 4, None)])[0]
        assert [k for k, _ in nres] == [k for k, _ in pres], query
        for (nk, ns), (pk, ps) in zip(nres, pres):
            assert abs(ns - ps) < 1e-9


def test_native_remove_and_update():
    try:
        idx, keys = _build(NativeBM25Index)
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    idx.remove(keys["d3"])
    assert len(idx) == 3
    res = idx.search([(None, "systolic", 4, None)])[0]
    assert [k for k, _ in res] == [keys["d1"]]
    # re-add with different text replaces the old posting
    idx.add(keys["d1"], "completely different words now")
    res2 = idx.search([(None, "systolic", 4, None)])[0]
    assert res2 == ()
    res3 = idx.search([(None, "different words", 4, None)])[0]
    assert [k for k, _ in res3] == [keys["d1"]]


def test_native_filtering_overfetch():
    try:
        idx, keys = _build(NativeBM25Index)
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    res = idx.search([(None, "systolic arrays matrix", 1,
                       lambda d: d and d["name"] == "d3")])[0]
    assert [k for k, _ in res] == [keys["d3"]]


def test_factory_prefers_native():
    idx = create_bm25_index()
    assert isinstance(idx, (NativeBM25Index, BM25Index))
    # in this image the toolchain exists, so native must win
    assert isinstance(idx, NativeBM25Index)


def test_selective_filter_escalates_fetch():
    """A filter passing only low-ranked docs must not shrink results
    (parity with BM25Index — over-fetch escalates past limit*4)."""
    try:
        native = NativeBM25Index()
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    python = BM25Index()
    keys = {}
    for i in range(50):
        k = hash_values(f"doc{i}")
        keys[i] = k
        # doc i repeats the query term i+1 times → rank increases with i
        text = " ".join(["match"] * (i + 1))
        fd = {"allowed": i < 5}  # only the 5 LOWEST-ranked docs pass
        native.add(k, text, filter_data=fd)
        python.add(k, text, filter_data=fd)
    filt = lambda d: bool(d and d["allowed"])
    nres = native.search([(None, "match", 3, filt)])[0]
    pres = python.search([(None, "match", 3, filt)])[0]
    assert len(nres) == len(pres) == 3
    assert {k for k, _ in nres} == {k for k, _ in pres}


def test_tie_break_parity_with_python_engine():
    """Equal-score hits must rank identically in both engines: by
    ascending Pointer (the Python engine's (-score, int(key)) sort key),
    NOT by native insertion-order doc id (the pre-fix divergence)."""
    try:
        native = NativeBM25Index()
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    python = BM25Index()
    # identical text → identical scores for every doc; scrambled insertion
    # order so insertion-order doc ids disagree with pointer order
    names = [f"doc{i:02d}" for i in range(20)]
    keys = {n: hash_values(n) for n in names}
    scrambled = sorted(names, key=lambda n: hash_values(n, 7))
    assert scrambled != sorted(names, key=lambda n: int(keys[n]))
    for n in scrambled:
        native.add(keys[n], "tied score text")
        python.add(keys[n], "tied score text")
    nres = native.search([(None, "tied text", 10, None)])[0]
    pres = python.search([(None, "tied text", 10, None)])[0]
    assert [k for k, _ in nres] == [k for k, _ in pres]
    assert [k for k, _ in nres] == sorted(keys.values(), key=int)[:10]


def test_re_add_clears_stale_filter_data():
    try:
        native = NativeBM25Index()
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    k = hash_values("doc")
    native.add(k, "hello world", filter_data={"ok": False})
    native.add(k, "hello world")  # re-add without metadata
    res = native.search([(None, "hello", 3, lambda d: d is None)])[0]
    assert [key for key, _ in res] == [k]


PHRASE_DOCS = [
    ("ring attention rotates key value blocks", 1),
    ("attention is all you need said the ring", 2),
    ("value networks rotate around the ring topology", 3),
]


def _build_pair(**kw):
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.bm25 import BM25Index, NativeBM25Index

    nat, py = NativeBM25Index(**kw), BM25Index(**kw)
    for text, i in PHRASE_DOCS:
        nat.add(Pointer(i), text)
        py.add(Pointer(i), text)
    return nat, py


def test_phrase_query_requires_adjacency_both_engines():
    from pathway_tpu.internals.keys import Pointer

    nat, py = _build_pair()
    for idx in (nat, py):
        # loose terms: every doc containing any term matches
        [loose] = idx.search([(Pointer(9), "ring attention", 10, None)])
        assert len(loose) == 3
        # quoted phrase: only the doc with the ADJACENT pair survives
        [phrase] = idx.search([(Pointer(9), '"ring attention"', 10, None)])
        assert [int(k) for k, _s in phrase] == [1]
        # phrase plus extra loose term still phrase-filters
        [mixed] = idx.search(
            [(Pointer(9), 'value "ring attention"', 10, None)])
        assert [int(k) for k, _s in mixed] == [1]


def test_stemming_toggle_both_engines():
    from pathway_tpu.internals.keys import Pointer

    # stemming on: 'rotates'/'rotate' collapse, so both docs match 'rotating'
    nat, py = _build_pair(stemming=True)
    for idx in (nat, py):
        [m] = idx.search([(Pointer(9), "rotating", 10, None)])
        assert {int(k) for k, _s in m} == {1, 3}
    # stemming off (default): no match for the unseen inflection
    nat2, py2 = _build_pair()
    for idx in (nat2, py2):
        [m] = idx.search([(Pointer(9), "rotating", 10, None)])
        assert m == ()


def test_native_persistence_survives_kill_and_restore(tmp_path):
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.bm25 import NativeBM25Index

    idx = NativeBM25Index(stemming=True)
    for text, i in PHRASE_DOCS:
        idx.add(Pointer(i), text, filter_data={"n": i})
    [before] = idx.search([(Pointer(9), '"ring attention"', 10, None)])
    path = tmp_path / "bm25.idx"
    path.write_bytes(idx.save_bytes())
    del idx  # 'kill'

    restored = NativeBM25Index.load_bytes(path.read_bytes())
    assert len(restored) == 3
    [after] = restored.search([(Pointer(9), '"ring attention"', 10, None)])
    assert [(int(k), round(s, 9)) for k, s in after] == \
        [(int(k), round(s, 9)) for k, s in before]
    # filters survive too
    [filt] = restored.search(
        [(Pointer(9), "ring", 10, lambda d: d and d["n"] == 3)])
    assert [int(k) for k, _s in filt] == [3]
    # incremental adds continue after restore
    restored.add(Pointer(7), "a brand new ring attention article")
    [again] = restored.search([(Pointer(9), '"ring attention"', 10, None)])
    assert {int(k) for k, _s in again} == {1, 7}


def test_truncated_bm25_blob_rejected():
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.bm25 import NativeBM25Index

    idx = NativeBM25Index()
    for text, i in PHRASE_DOCS:
        idx.add(Pointer(i), text)
    blob = idx.save_bytes()
    import pytest as _pytest

    for cut in (len(blob) - 3, len(blob) // 2, 10):
        with _pytest.raises(RuntimeError):
            NativeBM25Index.load_bytes(blob[:cut])
