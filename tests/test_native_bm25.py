"""Native C++ BM25 engine vs the Python engine
(native/text_index.cpp; reference equivalent: TantivyIndex,
src/external_integration/tantivy_integration.rs)."""

import pytest

from pathway_tpu.internals.keys import hash_values
from pathway_tpu.ops.bm25 import BM25Index, NativeBM25Index, create_bm25_index

DOCS = {
    "d1": "systolic arrays multiply matrices in hardware",
    "d2": "streaming dataflow engines process incremental updates",
    "d3": "the tpu matrix unit is a systolic array",
    "d4": "hash joins shuffle rows between workers",
}


def _build(cls):
    idx = cls()
    keys = {}
    for name, text in DOCS.items():
        keys[name] = hash_values(name)
        idx.add(keys[name], text, filter_data={"name": name})
    return idx, keys


def test_native_builds_and_matches_python_ranking():
    try:
        native, nkeys = _build(NativeBM25Index)
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    python, pkeys = _build(BM25Index)
    assert len(native) == len(python) == 4

    for query in ("systolic array", "incremental updates", "rows workers",
                  "nothing matches this zz"):
        nres = native.search([(None, query, 4, None)])[0]
        pres = python.search([(None, query, 4, None)])[0]
        assert [k for k, _ in nres] == [k for k, _ in pres], query
        for (nk, ns), (pk, ps) in zip(nres, pres):
            assert abs(ns - ps) < 1e-9


def test_native_remove_and_update():
    try:
        idx, keys = _build(NativeBM25Index)
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    idx.remove(keys["d3"])
    assert len(idx) == 3
    res = idx.search([(None, "systolic", 4, None)])[0]
    assert [k for k, _ in res] == [keys["d1"]]
    # re-add with different text replaces the old posting
    idx.add(keys["d1"], "completely different words now")
    res2 = idx.search([(None, "systolic", 4, None)])[0]
    assert res2 == ()
    res3 = idx.search([(None, "different words", 4, None)])[0]
    assert [k for k, _ in res3] == [keys["d1"]]


def test_native_filtering_overfetch():
    try:
        idx, keys = _build(NativeBM25Index)
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    res = idx.search([(None, "systolic arrays matrix", 1,
                       lambda d: d and d["name"] == "d3")])[0]
    assert [k for k, _ in res] == [keys["d3"]]


def test_factory_prefers_native():
    idx = create_bm25_index()
    assert isinstance(idx, (NativeBM25Index, BM25Index))
    # in this image the toolchain exists, so native must win
    assert isinstance(idx, NativeBM25Index)


def test_selective_filter_escalates_fetch():
    """A filter passing only low-ranked docs must not shrink results
    (parity with BM25Index — over-fetch escalates past limit*4)."""
    try:
        native = NativeBM25Index()
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    python = BM25Index()
    keys = {}
    for i in range(50):
        k = hash_values(f"doc{i}")
        keys[i] = k
        # doc i repeats the query term i+1 times → rank increases with i
        text = " ".join(["match"] * (i + 1))
        fd = {"allowed": i < 5}  # only the 5 LOWEST-ranked docs pass
        native.add(k, text, filter_data=fd)
        python.add(k, text, filter_data=fd)
    filt = lambda d: bool(d and d["allowed"])
    nres = native.search([(None, "match", 3, filt)])[0]
    pres = python.search([(None, "match", 3, filt)])[0]
    assert len(nres) == len(pres) == 3
    assert {k for k, _ in nres} == {k for k, _ in pres}


def test_tie_break_parity_with_python_engine():
    """Equal-score hits must rank identically in both engines: by
    ascending Pointer (the Python engine's (-score, int(key)) sort key),
    NOT by native insertion-order doc id (the pre-fix divergence)."""
    try:
        native = NativeBM25Index()
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    python = BM25Index()
    # identical text → identical scores for every doc; scrambled insertion
    # order so insertion-order doc ids disagree with pointer order
    names = [f"doc{i:02d}" for i in range(20)]
    keys = {n: hash_values(n) for n in names}
    scrambled = sorted(names, key=lambda n: hash_values(n, 7))
    assert scrambled != sorted(names, key=lambda n: int(keys[n]))
    for n in scrambled:
        native.add(keys[n], "tied score text")
        python.add(keys[n], "tied score text")
    nres = native.search([(None, "tied text", 10, None)])[0]
    pres = python.search([(None, "tied text", 10, None)])[0]
    assert [k for k, _ in nres] == [k for k, _ in pres]
    assert [k for k, _ in nres] == sorted(keys.values(), key=int)[:10]


def test_re_add_clears_stale_filter_data():
    try:
        native = NativeBM25Index()
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    k = hash_values("doc")
    native.add(k, "hello world", filter_data={"ok": False})
    native.add(k, "hello world")  # re-add without metadata
    res = native.search([(None, "hello", 3, lambda d: d is None)])[0]
    assert [key for key, _ in res] == [k]
