"""Seeded hung-reader canary: proves the watchdog gate bites.

A reader that claims liveness while producing nothing must trip the
watchdog, exhaust its (zero) retry budget, and terminate the run with
``ConnectorStalledError`` — within the deadline. Exits 0 iff exactly that
happened; any other outcome (run completes, wrong exception, hang past
the outer timeout) exits nonzero, failing the CI step.

Run: ``python tests/watchdog_canary.py`` (same pattern as the PR 2
shard-check canary: the gate is only trusted because a seeded failure is
proven to trip it).
"""

from __future__ import annotations

import sys

import pathway_tpu as pw
from pathway_tpu.testing.faults import hanging_subject


def main() -> int:
    subject = hanging_subject([{"word": "w"}], hang_attempts=-1)
    t = pw.io.python.read(
        subject, schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=10, persistent_id="canary",
        connector_policy=pw.ConnectorPolicy(max_retries=0))
    pw.io.subscribe(t, lambda *a, **k: None)
    try:
        pw.run(
            terminate_on_error=True,
            watchdog=pw.WatchdogConfig(reader_stall_timeout_s=0.5,
                                       tick_deadline_s=None,
                                       poll_interval_s=0.05))
    except pw.ConnectorStalledError as e:
        print(f"OK: watchdog fired and escalated: {e}")
        return 0
    except Exception as e:  # wrong failure mode: the gate is broken
        print(f"FAIL: expected ConnectorStalledError, got "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    print("FAIL: run completed without the watchdog firing",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
