"""Paged-store canary: online growth during live ingest, no
stop-the-world re-upload, ragged warmup bucket collapse.

Two gates (same pattern as pipelining_canary.py — the gate is trusted
because a seeded property is proven end to end):

1. **bench paging leg** (bench.bench_paging): identical chunked ingest
   through the paged store and the contiguous slab must produce
   byte-identical top-k, BOTH must grow, and the upload amplification
   (device rows written / rows ingested) must stay ~1.0 for the paged
   store while the slab re-ships its occupied slots after every growth.
   Ragged warmup must compile ≤ 6 shapes vs the ~18 width buckets.
   The leg's JSON is written as a CI artifact AND checkpointed into
   ``BENCH_LASTGOOD.json`` per the evidence rule.

2. **live engine ingest**: a streaming table feeds a paged KNN index
   through the real external-index operator across many commit ticks,
   forcing growth mid-stream; retrieval must stay exact and the pool
   must report the growth (grow_events >= 1, occupancy sane) — growth
   never stops the pipeline.

Exits 0 iff all hold. Run: ``python tests/paging_canary.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PATHWAY_PAGED_STORE", None)  # the default-on path is the DUT
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def gate_bench_leg() -> dict:
    import bench

    out = bench.bench_paging()
    bench._write_lastgood(out)  # evidence rule: checkpoint immediately
    artifact = os.environ.get("PAGING_BENCH_ARTIFACT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump(out, f, indent=1)
    assert out["paging_identical_topk"] is True, \
        "paged top-k diverged from the slab"
    assert out["paging_grow_events_paged"] >= 2, out
    assert out["paging_grow_events_slab"] >= 2, out
    amp_paged = out["paging_upload_amplification_paged"]
    amp_slab = out["paging_upload_amplification_slab"]
    assert amp_paged <= 1.5, (
        f"paged store re-uploaded {amp_paged}x the ingested rows — growth "
        f"is copying device state again")
    assert amp_slab >= amp_paged + 0.5, (
        f"slab amplification {amp_slab} vs paged {amp_paged}: the slab "
        f"baseline stopped re-uploading (measurement broken?)")
    assert out["paging_warmup_compiles_ragged"] <= 6, out
    assert out["paging_warmup_bucket_shapes"] >= 15, out
    print(f"[gate1] identical top-k; upload amplification paged "
          f"{amp_paged} vs slab {amp_slab}; ragged warmup "
          f"{out['paging_warmup_compiles_ragged']} compiles vs "
          f"{out['paging_warmup_bucket_shapes']} width buckets")
    return out


def gate_live_ingest() -> None:
    import numpy as np

    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index)

    G.clear()
    rng = np.random.default_rng(0)
    n, dim, ticks = 6000, 32, 8  # grows 1024 → 8192 across live ticks
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    schema = sch.schema_from_types(v=np.ndarray)
    rows = [(vecs[i], (i * ticks) // n * 2, 1) for i in range(n)]
    data = table_from_rows(schema, rows, is_stream=True)
    index = default_brute_force_knn_document_index(
        data.v, data, dimensions=dim, reserved_space=1024)
    qschema = sch.schema_from_types(qv=np.ndarray, k=int)
    queries = table_from_rows(qschema, [(vecs[4321], 3)])
    res = index.query_as_of_now(queries.qv, number_of_matches=queries.k)
    runner = GraphRunner()
    cap = runner.capture(res)
    runner.run_batch(n_workers=1)

    from pathway_tpu.engine.index_ops import ExternalIndexOperator
    from pathway_tpu.ops.knn import PagedKnnIndex

    ops = [node.op for node in runner.graph.nodes
           if isinstance(node.op, ExternalIndexOperator)]
    assert ops, "no external index operator in the canary graph"
    idx = ops[0].index
    assert isinstance(idx, PagedKnnIndex), type(idx)
    st = idx.page_stats()
    assert st["grow_events"] >= 1, st
    assert st["capacity_rows"] >= n, st
    assert 0.0 < st["occupancy"] <= 1.0, st
    final = [row for _, row, _, diff in cap.events if diff > 0]
    assert final, "no retrieval answer produced"
    reply = final[-1][0]
    assert reply, "empty retrieval under live growth"
    G.clear()
    print(f"[gate2] live ingest grew the store {st['grow_events']}x to "
          f"{st['capacity_rows']} rows ({st['pages_total']} pages) with "
          f"retrieval intact")


def main() -> int:
    gate_bench_leg()
    gate_live_ingest()
    print("paging canary: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
