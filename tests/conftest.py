"""Test env: force CPU backend with 8 virtual devices so multi-chip sharding
tests run without TPU hardware (SURVEY §4: the stand-in for the reference's
fork-based multi-process tests).

Note: the axon sitecustomize imports jax at interpreter startup (before this
conftest), so env vars (JAX_PLATFORMS / XLA_FLAGS) are too late — but jax
backends initialize lazily, so jax.config.update still wins as long as no
devices were touched yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")
