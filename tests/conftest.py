"""Test env: force CPU backend with 8 virtual devices so multi-chip sharding
tests run without TPU hardware (SURVEY §4: the stand-in for the reference's
fork-based multi-process tests)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
