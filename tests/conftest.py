"""Test env: force CPU backend with 8 virtual devices so multi-chip sharding
tests run without TPU hardware (SURVEY §4: the stand-in for the reference's
fork-based multi-process tests).

Note: the axon sitecustomize imports jax at interpreter startup (before this
conftest), so env vars (JAX_PLATFORMS / XLA_FLAGS) are too late — but jax
backends initialize lazily, so jax.config.update still wins as long as no
devices were touched yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# XLA reads this flag at (lazy) backend init, so it still applies when jax
# was already imported — the fallback for jax versions without the
# jax_num_cpu_devices config option
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: XLA_FLAGS above provides the 8 virtual devices


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leaked_reader_threads():
    """Every test must leave no live connector reader threads behind: a
    leaked poll thread in a long-lived process is a real bug (round-3
    finding — the sharepoint poller outlived the whole suite). Runtimes
    started on background threads are stopped via the registry."""
    yield
    import threading
    import time

    from pathway_tpu.engine import streaming

    streaming.stop_all(join_timeout=5.0)
    deadline = time.monotonic() + 5.0
    leaked = []
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.name.startswith("pathway-tpu-src-") and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"leaked connector reader threads: {leaked}"
