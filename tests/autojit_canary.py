"""Auto-jit canary: the framework-vs-raw throughput gate + the trace
artifact (internals/autojit.py, VERDICT #5).

One gate, evidence-first (same pattern as paging_canary.py):

**bench autojit leg** (bench.bench_autojit): the SAME doc-scoring
pipeline — traceable/vmappable scalar UDF chain + host-only formatter +
batch device embed payload — measured three ways in interleaved
best-of-3 trials: raw hand-written kernels, Table path with auto-jit ON,
Table path with auto-jit OFF. Gates:

- ``framework_vs_raw_ratio`` (ON) >= 0.85 — the ROADMAP/VERDICT target;
- the OFF ratio reproduces today's gap (strictly below the ON ratio —
  the artifact carries both numbers from the same run);
- the three paths are byte-identical (asserted inside the leg);
- the fused tier really ran: programs >= 1, dispatches > 0, ZERO
  demotions, and warmup walked the bucket ladder (first-tick compiles
  out of serving latency);
- the flight-recorder per-stage breakdown for BOTH modes ships in the
  trace artifact (``AUTOJIT_TRACE_ARTIFACT``) — the "where the
  Table-path tax went" evidence, uploaded by CI.

The leg's JSON is checkpointed into ``BENCH_LASTGOOD.json`` per the
evidence rule. The ratio gate retries once: on a 2-core shared runner a
neighbor-load episode can straddle even interleaved trials (the r05
lesson — trace_canary's overhead guard retries for the same reason).

Exits 0 iff all hold. Run: ``python tests/autojit_canary.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PATHWAY_AUTO_JIT", None)  # the default-on path is the DUT
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

RATIO_GATE = float(os.environ.get("AUTOJIT_RATIO_GATE", "0.85"))


def run_leg() -> dict:
    import bench

    artifact = os.environ.get("AUTOJIT_TRACE_ARTIFACT")
    if artifact:
        os.environ["BENCH_AUTOJIT_TRACE_ARTIFACT"] = artifact
    out = bench.bench_autojit()
    bench._write_lastgood(out)  # evidence rule: checkpoint immediately
    json_artifact = os.environ.get("AUTOJIT_BENCH_ARTIFACT")
    if json_artifact:
        with open(json_artifact, "w") as f:
            json.dump(out, f, indent=1)
    return out


def gate(out: dict) -> None:
    ratio = out["framework_vs_raw_ratio"]
    nojit = out["framework_vs_raw_ratio_nojit"]
    assert out["autojit_programs"] >= 1, out
    assert (out["autojit_device_dispatches"]
            + out["autojit_vector_dispatches"]) > 0, \
        "fused tier never dispatched — the gate would be vacuous"
    assert out["autojit_demotions"] == 0, (
        f"{out['autojit_demotions']} demotion(s) during the bench leg — "
        f"a chain the static gates admitted failed on real data")
    assert out["autojit_warmup_compiles"] >= 1, \
        "pw.warmup walked no auto-jit buckets"
    assert nojit < ratio, (
        f"auto-jit OFF ({nojit}) did not reproduce the gap below ON "
        f"({ratio}) — the comparison is not measuring the tier")
    assert ratio >= RATIO_GATE, (
        f"framework_vs_raw_ratio {ratio} < {RATIO_GATE} "
        f"(nojit ratio {nojit})")


def main() -> None:
    out = run_leg()
    try:
        gate(out)
    except AssertionError as first:
        # one retry for runner-noise resilience; both artifacts kept
        print(f"[autojit-canary] first attempt failed ({first}); retrying "
              f"once for shared-runner noise", flush=True)
        out = run_leg()
        gate(out)
    trace = os.environ.get("AUTOJIT_TRACE_ARTIFACT")
    if trace:
        with open(trace) as f:
            t = json.load(f)
        assert t["per_stage_ms"]["on"] and t["per_stage_ms"]["off"], t
    print(f"[autojit-canary] OK: framework_vs_raw_ratio "
          f"{out['framework_vs_raw_ratio']} (gate {RATIO_GATE}), "
          f"nojit {out['framework_vs_raw_ratio_nojit']}, "
          f"{out['autojit_programs']} program(s), "
          f"{out['autojit_device_dispatches']} device + "
          f"{out['autojit_vector_dispatches']} vector dispatches, "
          f"{out['autojit_warmup_compiles']} warmup compiles, "
          f"0 demotions")


if __name__ == "__main__":
    main()
