"""Serving-path SLO tracing (engine/request_tracker.py + io/http):

- P² streaming quantile estimators track numpy percentiles and the
  exposed p50/p95/p99 set is always monotone;
- the per-stage decomposition telescopes: stages sum to the wall-clock
  e2e total, including under a fault-injected delay that must land in
  the right stage;
- end to end through a real rest_connector pipeline: request id assigned
  at ingress and echoed in X-Pathway-Request-Id, every stage stamped,
  /metrics exposes the new families under the same exposition lint as
  PR 5's, slow queries surface on /status, request spans join the
  Perfetto trace as a third track with flow links — and pipeline outputs
  are byte-identical with tracing on or off.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.flight_recorder import FlightRecorder
from pathway_tpu.engine.request_tracker import (STAGES, P2Quantile,
                                                RequestTracker)
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import faults


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    faults.reset()
    yield
    G.clear()
    faults.reset()


# ---------------------------------------------------------------------------
# P² quantile estimator
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("q", [0.5, 0.95, 0.99])
def test_p2_tracks_numpy_percentile(q):
    rng = np.random.default_rng(0)
    xs = rng.lognormal(mean=2.0, sigma=0.6, size=4000)
    est = P2Quantile(q)
    for x in xs:
        est.observe(float(x))
    exact = float(np.percentile(xs, q * 100))
    assert est.value() == pytest.approx(exact, rel=0.08)


def test_p2_exact_below_five_samples():
    est = P2Quantile(0.5)
    assert est.value() is None
    for x in (5.0, 1.0, 3.0):
        est.observe(x)
    assert est.value() == 3.0  # exact median of the tiny prefix


def test_reported_quantiles_are_monotone():
    tr = RequestTracker(slo_ms=1e9)
    rng = np.random.default_rng(1)
    for i, ms in enumerate(rng.exponential(10.0, size=500)):
        span = tr.start(f"r{i}", "/q", t_ingress=float(i))
        span.key = i
        tr._by_key[i] = span
        span.t_enqueued = float(i)
        span.t_resolved = float(i) + ms / 1e3
        tr.finish(span)
    qs = tr.quantiles_ms()
    assert qs is not None
    assert qs[0.5] <= qs[0.95] <= qs[0.99]


# ---------------------------------------------------------------------------
# stage decomposition telescopes
# ---------------------------------------------------------------------------

def _synthetic_span(tr, rid="r1", *, enq=0.002, tick=0.010,
                    host=0.020, dev=0.015):
    # anchored so t_resolved ~= now: finish() stamps t_responded with the
    # real clock, keeping the response_write stage tiny as in production
    t0 = time.perf_counter() - (enq + tick + host + dev)
    span = tr.start(rid, "/q", t_ingress=t0)
    tr.enqueued(span, rid)
    span.t_enqueued = t0 + enq
    tr.picked_up([(rid, (), 1)], tick=7)
    span.t_tick_start = t0 + enq + tick
    tr.host_done(7)
    span.t_host_done = t0 + enq + tick + host
    tr.resolved(rid)
    span.t_resolved = t0 + enq + tick + host + dev
    tr.finish(span)
    return span, tr.completed[-1]


def test_stages_sum_to_e2e():
    tr = RequestTracker()
    span, rec = _synthetic_span(tr)
    stages = span.stages_ms()
    e2e = (span.normalized_stamps()[-1] - span.t_ingress) * 1e3
    assert sum(stages.values()) == pytest.approx(e2e, abs=1e-9)
    assert set(stages) == set(STAGES)
    assert rec["tick"] == 7


def test_out_of_order_and_missing_stamps_clamp_but_still_sum():
    tr = RequestTracker()
    span = tr.start("r2", "/q", t_ingress=10.0)
    tr.enqueued(span, "r2")
    span.t_enqueued = 10.001
    # never picked up / host-done (e.g. resolved inside the same host
    # leg in synchronous mode): those stamps stay None
    span.t_resolved = 10.050
    tr.finish(span)
    stages = span.stages_ms()
    assert stages["queue"] == 0.0 and stages["host"] == 0.0
    assert sum(stages.values()) == pytest.approx(
        (span.t_responded - 10.0) * 1e3, rel=1e-9)


def test_unresolved_span_is_abandoned_not_aggregated():
    tr = RequestTracker()
    span = tr.start("gone", "/q", t_ingress=1.0)
    tr.enqueued(span, "gone")
    tr.finish(span)  # client disconnected before the pipeline answered
    assert tr.count == 0
    assert "gone" not in tr._by_key


def test_slow_query_tail_names_dominant_stage():
    tr = RequestTracker(slo_ms=10.0)
    _synthetic_span(tr, "slow1", host=0.200)  # host dominates, way over
    slow = tr.slow_queries()
    assert len(slow) == 1
    assert slow[0]["request_id"] == "slow1"
    assert slow[0]["dominant_stage"] == "host"
    assert slow[0]["e2e_ms"] > 10.0
    assert tr.burn_rate() > 1.0  # 100% violations vs 1% budget


# ---------------------------------------------------------------------------
# end to end: rest_connector pipeline under the streaming runtime
# ---------------------------------------------------------------------------

@pw.udf(deterministic=True)
def _slow_upper(q: str) -> str:
    faults.hit("serving.handler.delay")
    return q.upper()


def _run_rest_pipeline(monkeypatch, queries: list[str],
                       recorder_on: bool) -> dict:
    """Serve ``queries`` through a real rest_connector pipeline; returns
    {query: (answer, request_id)} plus the runtime's tracker snapshot."""
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    G.clear()
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER",
                       "1" if recorder_on else "0")
    ws = PathwayWebserver(host="127.0.0.1", port=0)
    schema = sch.schema_from_types(query=str)
    table, writer = rest_connector(
        webserver=ws, route="/q", schema=schema, methods=("POST",),
        delete_completed_queries=True, autocommit_duration_ms=10)
    writer(table.select(result=_slow_upper(table.query)))

    errors = []

    def _run():
        try:
            pw.run()
        except Exception as e:  # surfaced by the assert below
            errors.append(e)

    th = threading.Thread(target=_run, daemon=True)
    th.start()
    deadline = time.monotonic() + 20.0
    rt = None
    while time.monotonic() < deadline:
        live = list(_streaming._ACTIVE_RUNTIMES)
        if live and ws._started.is_set() and ws.port:
            rt = live[0]
            break
        time.sleep(0.02)
    assert rt is not None and not errors, f"runtime never started: {errors}"
    out = {}
    try:
        for q in queries:
            req = urllib.request.Request(
                f"http://127.0.0.1:{ws.port}/q",
                data=json.dumps({"query": q}).encode(), method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=15) as resp:
                body = resp.read().decode()
                rid = resp.headers.get("X-Pathway-Request-Id")
            out[q] = (body, rid)
        tracker = rt.recorder.requests if rt.recorder is not None else None
        snapshot = {
            "summary": tracker.summary() if tracker else None,
            "completed": tracker.trace_spans() if tracker else [],
            "recorder": rt.recorder,
        }
    finally:
        _streaming.stop_all()
        th.join(10.0)
        G.clear()
    assert not errors, f"pipeline failed: {errors}"
    return {"responses": out, **snapshot}


def test_rest_pipeline_stamps_every_stage_and_sums(monkeypatch):
    # fault-injected delay inside the UDF: it executes during the
    # scheduler tick, so the decomposition must charge it to the
    # host/device stages — and the stages must still sum to e2e
    with faults.arm("serving.handler.delay", faults.Delay(0.05)):
        res = _run_rest_pipeline(monkeypatch, ["hello", "world"],
                                 recorder_on=True)
    for q, (body, rid) in res["responses"].items():
        assert body == q.upper()
        assert rid, "X-Pathway-Request-Id header missing"
    completed = res["completed"]
    assert len(completed) == 2
    for rec in completed:
        stages = rec["stages"]
        assert set(stages) == set(STAGES)
        assert all(v >= 0.0 for v in stages.values())
        assert sum(stages.values()) == pytest.approx(rec["e2e_ms"],
                                                     abs=0.01)
        # the injected 50ms lives in the compute stages, not in
        # ingress/queue/response bookkeeping
        assert stages["host"] + stages["device"] >= 45.0
        assert rec["tick"] is not None
    summary = res["summary"]
    assert summary["requests"] == 2
    assert summary["e2e_ms"]["p50"] >= 50.0


def test_rest_pipeline_outputs_identical_with_tracing_off(monkeypatch):
    queries = ["alpha", "beta", "gamma"]
    on = _run_rest_pipeline(monkeypatch, queries, recorder_on=True)
    off = _run_rest_pipeline(monkeypatch, queries, recorder_on=False)
    assert off["summary"] is None  # recorder (and tracker) truly off
    assert {q: body for q, (body, _r) in on["responses"].items()} == \
        {q: body for q, (body, _r) in off["responses"].items()}


def test_rest_pipeline_metrics_and_status_surfaces(monkeypatch):
    from pathway_tpu.engine.http_server import MonitoringHttpServer
    from tests.test_monitoring_http import _parse_samples

    monkeypatch.setenv("PATHWAY_SLO_E2E_MS", "0.000001")  # everything slow
    res = _run_rest_pipeline(monkeypatch, ["one", "two"], recorder_on=True)

    class _RT:  # minimal runtime shell around the finished scheduler state
        class scheduler:
            recorder = res["recorder"]
            stats: dict = {}

        class runner:
            class graph:
                nodes: list = []

        sessions: list = []

    server = MonitoringHttpServer(_RT(), port=0)
    lines = server.metrics_payload().splitlines()
    samples = _parse_samples(lines)  # regex lint over every line
    fam = {f for f, _l, _v in samples}
    assert "pathway_tpu_query_e2e_latency_ms" in fam
    assert "pathway_tpu_slo_burn_rate" in fam
    typed = {ln.split()[2] for ln in lines if ln.startswith("# TYPE")}
    assert {"pathway_tpu_query_e2e_latency_ms", "pathway_tpu_query_stage_ms",
            "pathway_tpu_query_slo_violations",
            "pathway_tpu_slo_burn_rate"} <= typed
    # quantile monotonicity straight off the exposition text
    qv = {lab["quantile"]: v for f, lab, v in samples
          if f == "pathway_tpu_query_e2e_latency_ms" and "quantile" in lab}
    assert qv["0.5"] <= qv["0.95"] <= qv["0.99"]
    counts = [v for f, _l, v in samples
              if f == "pathway_tpu_query_e2e_latency_ms_count"]
    assert counts == [2.0]
    stage_labels = {lab["stage"] for f, lab, _v in samples
                    if f.startswith("pathway_tpu_query_stage_ms")}
    assert stage_labels == set(STAGES)
    # /status: serving summary + over-budget tail with dominant stage
    status = server.status_payload()
    assert status["serving"]["requests"] == 2
    assert len(status["slow_queries"]) == 2  # SLO pinned near zero
    assert status["slow_queries"][-1]["dominant_stage"] in STAGES


def test_request_spans_join_perfetto_trace_with_flow_links(monkeypatch):
    res = _run_rest_pipeline(monkeypatch, ["link me"], recorder_on=True)
    rec: FlightRecorder = res["recorder"]
    events = rec.chrome_trace_events()
    meta = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert "requests" in meta  # the third track
    req_b = [e for e in events
             if e["ph"] == "b" and e["name"].startswith("req ")]
    assert req_b, "no request span in the trace"
    span = req_b[0]
    assert span["tid"] == 2 and span["cat"] == "request"
    assert span["args"]["tick"] is not None
    # every async b has a matching e per (id, name)
    for b in [e for e in events if e["ph"] == "b"]:
        assert any(e["ph"] == "e" and e["id"] == b["id"]
                   and e["name"] == b["name"] for e in events)
    # flow: s on the request track, t/f landing on host/device wrappers
    flows = [e for e in events if e["ph"] in ("s", "t", "f")
             and e.get("cat") == "request"]
    assert any(e["ph"] == "s" and e["tid"] == 2 for e in flows)
    sinks = [e for e in flows if e["ph"] in ("t", "f")]
    assert sinks and all(e["tid"] in (0, 1) for e in sinks)
    # sync-slice (B/E) nesting untouched by the async request events
    stacks: dict = {}
    for e in events:
        if e["ph"] == "B":
            stacks.setdefault(e["tid"], []).append(e["name"])
        elif e["ph"] == "E":
            assert stacks.get(e["tid"]), "E without B"
            assert stacks[e["tid"]].pop() == e["name"]
    assert not any(stacks.values())


# ---------------------------------------------------------------------------
# atomic trace write
# ---------------------------------------------------------------------------

def test_trace_write_is_atomic_on_failure(tmp_path, monkeypatch):
    """A crash mid-serialization must neither truncate an existing trace
    nor leave a tmp file behind."""
    import pathway_tpu.engine.flight_recorder as fr

    path = tmp_path / "trace.json"
    rec = FlightRecorder(trace_path=str(path))
    rec.enabled = True

    class _N:
        id = 0
        name = "op"
        op = object()
        trace = None

    rec.record(1, _N(), "host", 0.0, 1.0, 1, 1)
    assert rec.write_chrome_trace() == str(path)
    good = path.read_text()
    assert json.loads(good)["traceEvents"]

    real_dump = json.dump

    def boom(obj, f, *a, **k):
        f.write('{"traceEvents": [truncat')  # partial bytes, then die
        raise OSError("disk full")

    monkeypatch.setattr(fr.json, "dump", boom)
    with pytest.raises(OSError):
        rec.write_chrome_trace()
    monkeypatch.setattr(fr.json, "dump", real_dump)
    assert path.read_text() == good  # previous good trace intact
    leftovers = [p for p in path.parent.iterdir() if ".tmp" in p.name]
    assert not leftovers, f"tmp files left behind: {leftovers}"
