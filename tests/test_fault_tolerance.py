"""Supervised streaming runtime under injected faults
(engine/supervisor.py + pathway_tpu/testing/faults.py; reference: the
per-connector input threads whose death the main loop observes,
src/connectors/mod.rs, and the wordcount kill-and-recover harness).

Proves the acceptance contract of the supervision layer:
- a reader that crashes mid-stream is restarted with backoff and, under
  persistence, the final output is byte-identical to the no-fault run
  (exactly-once across in-process restarts AND process re-runs);
- with retries exhausted, ``terminate_on_error=True`` makes ``pw.run``
  re-raise the connector's own exception (reader-thread traceback
  attached) while ``terminate_on_error=False`` keeps the remaining
  sources serving with the failure visible in the ErrorLog, ``/healthz``
  (503) and ``/metrics``;
- the watchdog fires on a reader that stops producing while claiming
  liveness, and its escalation heals the pipeline when retries allow.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
import urllib.error
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.retries import FixedDelayRetryStrategy
from pathway_tpu.testing import faults
from pathway_tpu.testing.faults import (InjectedFault, flaky_subject,
                                        hanging_subject)


@pytest.fixture(autouse=True)
def _fresh():
    G.clear()
    faults.reset()
    yield
    G.clear()
    faults.reset()


def _rows(words):
    return [{"word": w} for w in words]


def _fast_policy(max_retries=3):
    return pw.ConnectorPolicy(
        max_retries=max_retries,
        retry_strategy=FixedDelayRetryStrategy(delay_ms=20))


def _run_counts(subject, *, backend=None, policy=None, persistent_id="words",
                **run_kwargs) -> dict:
    """Stream word rows from ``subject``, return final word counts."""
    G.clear()
    t = pw.io.python.read(
        subject, schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=10, persistent_id=persistent_id,
        connector_policy=policy)
    counts = t.groupby(t.word).reduce(word=t.word, c=pw.reducers.count())
    state: dict[str, int] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["word"]] = row["c"]
        elif state.get(row["word"]) == row["c"]:
            del state[row["word"]]

    pw.io.subscribe(counts, on_change)
    cfg = None
    if backend is not None:
        cfg = pw.persistence.Config.simple_config(backend)
    pw.run(persistence_config=cfg, **run_kwargs)
    return state


# ---------------------------------------------------------------------------
# crash → backoff restart → exactly-once
# ---------------------------------------------------------------------------

WORDS = ["a", "b", "a", "c", "b", "a"]


def test_crash_restart_exactly_once_without_persistence():
    """In-process restart must not double-deliver: the supervisor skips
    the prefix the crashed attempt already pushed."""
    baseline = _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                         fail_attempts=0))
    assert baseline == {"a": 3, "b": 2, "c": 1}
    subject = flaky_subject(_rows(WORDS), fail_after=3, fail_attempts=1)
    state = _run_counts(subject, policy=_fast_policy())
    assert state == baseline
    assert type(subject).attempts == 2  # initial run + one restart


def test_crash_restart_exactly_once_with_persistence_byte_identical():
    """Two consecutive crashes, restarts under backoff, persistence
    recording throughout: the serialized final output must be
    byte-identical to the no-fault run's."""
    baseline = _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                         fail_attempts=0))
    backend = pw.persistence.Backend.mock()
    subject = flaky_subject(_rows(WORDS), fail_after=3, fail_attempts=2)
    state = _run_counts(subject, backend=backend, policy=_fast_policy())
    assert type(subject).attempts == 3
    as_bytes = json.dumps(sorted(state.items())).encode()
    assert as_bytes == json.dumps(sorted(baseline.items())).encode()
    # the durable log replays to the same state on a fresh process-run
    replay = _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                       fail_attempts=0), backend=backend)
    assert replay == baseline


def test_double_crash_process_restarts_replay_exactly_once():
    """Two consecutive process crashes (terminate_on_error=True raises,
    simulating the kill), each at a different stream position, then a
    clean run: replay+skip must hold across crash-of-a-recovery."""
    backend = pw.persistence.Backend.mock()
    words = ["a", "b", "a", "c"]
    for fail_after in (2, 3):  # second crash strictly later in the stream
        subject = flaky_subject(_rows(words), fail_after=fail_after,
                                fail_attempts=-1, delay_s=0.03)
        with pytest.raises(InjectedFault):
            _run_counts(subject, backend=backend,
                        policy=pw.ConnectorPolicy(max_retries=0),
                        terminate_on_error=True)
    state = _run_counts(flaky_subject(_rows(words), fail_after=0,
                                      fail_attempts=0), backend=backend)
    assert state == {"a": 2, "b": 1, "c": 1}


# ---------------------------------------------------------------------------
# retries exhausted → escalation per terminate_on_error
# ---------------------------------------------------------------------------

def test_terminate_on_error_true_reraises_connector_exception():
    subject = flaky_subject(_rows(WORDS), fail_after=2, fail_attempts=-1)
    with pytest.raises(InjectedFault) as exc_info:
        _run_counts(subject, policy=_fast_policy(max_retries=1),
                    terminate_on_error=True)
    assert type(subject).attempts == 2  # initial + the single allowed retry
    # the reader thread's traceback rides along to pw.run's caller
    frames = traceback.extract_tb(exc_info.value.__traceback__)
    assert any("faults.py" in f.filename for f in frames)


def test_terminate_on_error_false_keeps_serving_and_logs():
    G.clear()
    schema = pw.schema_from_types(word=str)
    bad = pw.io.python.read(
        flaky_subject(_rows(["x", "x"]), fail_after=1, fail_attempts=-1),
        schema=schema, autocommit_duration_ms=10, persistent_id="bad",
        connector_policy=_fast_policy(max_retries=1))
    good = pw.io.python.read(
        flaky_subject(_rows(["g", "g", "g"]), fail_after=0, fail_attempts=0),
        schema=schema, autocommit_duration_ms=10, persistent_id="good")
    good_state: dict[str, int] = {}
    bad_state: dict[str, int] = {}

    def updater(state):
        def on_change(key, row, time, is_addition):
            if is_addition:
                state[row["word"]] = row["c"]
        return on_change

    pw.io.subscribe(bad.groupby(bad.word).reduce(
        word=bad.word, c=pw.reducers.count()), updater(bad_state))
    pw.io.subscribe(good.groupby(good.word).reduce(
        word=good.word, c=pw.reducers.count()), updater(good_state))
    n_before = len(pw.global_error_log().connector_failures())
    pw.run(terminate_on_error=False)  # completes despite the dead source
    # the healthy source served to completion
    assert good_state == {"g": 3}
    # the failure is visible, never laundered into a clean shutdown
    failures = pw.global_error_log().connector_failures()[n_before:]
    assert any("'bad'" in f["message"] for f in failures)
    assert all(f["kind"] == "connector" for f in failures)


def _build_streaming_runtime(**kw):
    from pathway_tpu.engine.streaming import StreamingRuntime
    from pathway_tpu.internals.runner import GraphRunner

    runner = GraphRunner()
    for binder in G.output_binders:
        binder(runner)
    return StreamingRuntime(runner, **kw)


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_healthz_503_and_metrics_for_failed_source(monkeypatch):
    """Degraded-but-serving runtime: /healthz flips to 503 naming the
    failed source and its retry count; /metrics carries the connector
    counters."""
    monkeypatch.setenv("PATHWAY_MONITORING_HTTP_PORT", "0")  # ephemeral
    G.clear()
    schema = pw.schema_from_types(word=str)
    bad = pw.io.python.read(
        flaky_subject(_rows(["x"]), fail_after=0, fail_attempts=-1),
        schema=schema, autocommit_duration_ms=10, persistent_id="bad",
        connector_policy=pw.ConnectorPolicy(
            max_retries=1, retry_strategy=FixedDelayRetryStrategy(
                delay_ms=10)))
    keeper = pw.io.python.read(
        hanging_subject(_rows(["k"])), schema=schema,
        autocommit_duration_ms=10, persistent_id="keeper")
    pw.io.subscribe(bad, lambda *a, **k: None)
    pw.io.subscribe(keeper, lambda *a, **k: None)
    rt = _build_streaming_runtime(with_http_server=True,
                                  terminate_on_error=False)
    th = threading.Thread(target=rt.run, daemon=True)
    th.start()
    try:
        deadline = time.monotonic() + 15
        code, body = None, ""
        while time.monotonic() < deadline:
            if rt.http_server._httpd is not None:
                base = f"http://127.0.0.1:{rt.http_server.port}"
                code, body = _get(base + "/healthz")
                if code == 503:
                    break
            time.sleep(0.05)
        assert code == 503, f"healthz never degraded: {code} {body}"
        payload = json.loads(body)
        assert payload["status"] == "degraded"
        assert [f["source"] for f in payload["failed_sources"]] == ["bad"]
        assert payload["failed_sources"][0]["restarts"] == 1
        assert payload["connector_retries"]["bad"] == 1
        code, metrics = _get(base + "/metrics")
        assert code == 200
        assert 'pathway_tpu_connector_failed{source="bad"} 1' in metrics
        assert 'pathway_tpu_connector_restarts{source="bad"} 1' in metrics
        assert 'pathway_tpu_connector_failed{source="keeper"} 0' in metrics
    finally:
        rt.stop()
        th.join(10)
    assert not th.is_alive()


# ---------------------------------------------------------------------------
# watchdog: hung readers and connect timeouts
# ---------------------------------------------------------------------------

def test_watchdog_fires_on_hung_reader_and_escalates():
    subject = hanging_subject(_rows(["a", "b"]))  # hangs on every attempt
    with pytest.raises(pw.ConnectorStalledError, match="claiming liveness"):
        _run_counts(
            subject, policy=pw.ConnectorPolicy(max_retries=0),
            terminate_on_error=True,
            watchdog=pw.WatchdogConfig(reader_stall_timeout_s=0.3,
                                       tick_deadline_s=None,
                                       poll_interval_s=0.05))


def test_watchdog_triggered_restart_heals_pipeline():
    """First attempt hangs mid-stream; the watchdog abandons it and the
    supervisor's restart finishes the stream — exactly once."""
    subject = hanging_subject(_rows(WORDS), hang_attempts=1)
    state = _run_counts(
        subject, policy=_fast_policy(max_retries=2),
        watchdog=pw.WatchdogConfig(reader_stall_timeout_s=0.25,
                                   tick_deadline_s=None,
                                   poll_interval_s=0.05))
    assert state == {"a": 3, "b": 2, "c": 1}
    assert type(subject).attempts == 2


def test_connect_timeout_counts_as_failed_attempt():
    """A reader silent from birth (no push, no heartbeat, no close) is
    abandoned after connect_timeout and restarted."""

    class _SilentFirst(pw.io.python.ConnectorSubject):
        attempts = 0

        def run(self):
            attempt = type(self).attempts
            type(self).attempts = attempt + 1
            if attempt == 0:
                while not self._session.stop_requested:
                    time.sleep(0.01)
                return
            for values in _rows(["a", "b"]):
                self.next(**values)

    subject = _SilentFirst()
    state = _run_counts(
        subject,
        policy=pw.ConnectorPolicy(
            max_retries=1,
            retry_strategy=FixedDelayRetryStrategy(delay_ms=10),
            connect_timeout=0.3))
    assert state == {"a": 1, "b": 1}
    assert type(subject).attempts == 2


# ---------------------------------------------------------------------------
# fault-point machinery
# ---------------------------------------------------------------------------

def test_fault_points_unarmed_are_noops():
    faults.hit("nonexistent.point")  # must not raise


def test_fail_n_times_then_passes():
    action = faults.FailNTimes(2)
    with faults.arm("p", action):
        for _ in range(2):
            with pytest.raises(InjectedFault):
                faults.hit("p")
        faults.hit("p")  # third hit passes
    faults.hit("p")  # disarmed


def test_fail_on_exact_hit():
    with faults.arm("p", faults.FailOnHit(3)):
        faults.hit("p")
        faults.hit("p")
        with pytest.raises(InjectedFault):
            faults.hit("p")
        faults.hit("p")


def test_delay_action_delays():
    with faults.arm("cluster.exchange.delay", faults.Delay(0.15, times=1)):
        t0 = time.monotonic()
        faults.hit("cluster.exchange.delay")
        assert time.monotonic() - t0 >= 0.15
        t0 = time.monotonic()
        faults.hit("cluster.exchange.delay")  # only the first hit delays
        assert time.monotonic() - t0 < 0.1


def test_resuming_source_restarts_without_prefix_skip():
    """A source that resumes from externally-tracked offsets (e.g. a
    Kafka consumer group) re-emits NOTHING on restart — prefix-skip would
    silently drop fresh rows. restart_resumes=True must disable it."""
    from pathway_tpu.io._datasource import DataSource

    class _Resuming(DataSource):
        name = "resuming"
        restart_resumes = True
        attempts = 0

        def run(self, session):
            attempt = type(self).attempts
            type(self).attempts = attempt + 1
            words = ["a", "b", "a", "c"]
            if attempt == 0:
                for i, w in enumerate(words[:2]):
                    session.push(*self.row_to_engine({"word": w}, i))
                raise InjectedFault("crash after committing offsets")
            # resumed: only the rows past the crash point, like a consumer
            # group continuing from its committed offset
            for i, w in enumerate(words[2:], start=2):
                session.push(*self.row_to_engine({"word": w}, i))

    from pathway_tpu.internals.table import Plan, Table
    from pathway_tpu.internals.universe import Universe

    G.clear()
    schema = pw.schema_from_types(word=str)
    source = _Resuming(schema, autocommit_duration_ms=10)
    source.persistent_id = "resuming"
    source.connector_policy = _fast_policy()
    t = Table(Plan("input", datasource=source), schema, Universe(),
              name="resuming")
    counts = t.groupby(t.word).reduce(word=t.word, c=pw.reducers.count())
    state: dict[str, int] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["word"]] = row["c"]
        elif state.get(row["word"]) == row["c"]:
            del state[row["word"]]

    pw.io.subscribe(counts, on_change)
    pw.run()
    assert _Resuming.attempts == 2
    assert state == {"a": 2, "b": 1, "c": 1}  # nothing dropped, no dupes


def test_kafka_group_id_marks_source_resuming():
    t = pw.io.kafka.read({"bootstrap.servers": "x", "group.id": "g"},
                         "topic")
    assert t._plan.params["datasource"].restart_resumes
    t2 = pw.io.kafka.read({"bootstrap.servers": "x"}, "topic")
    assert not t2._plan.params["datasource"].restart_resumes


def test_stop_all_stops_collect_sessions():
    """Process-level teardown (streaming.stop_all) must reach static-mode
    connectors sleeping through a CollectSession."""
    from pathway_tpu.engine import streaming
    from pathway_tpu.io._datasource import CollectSession

    cs = CollectSession()
    assert cs.sleep(0.01) is True
    streaming.stop_all()
    assert cs.stop_requested
    assert cs.sleep(30.0) is False  # returns immediately


def test_detached_attempt_records_no_liveness():
    """An abandoned zombie attempt must not heartbeat through the shared
    entry — it would mask a hung replacement attempt from the watchdog
    and falsify the connect-timeout baseline."""
    from types import SimpleNamespace

    from pathway_tpu.engine.supervisor import (ConnectorSupervisor,
                                               _SupervisedSession)
    from pathway_tpu.io._datasource import Session

    sup = ConnectorSupervisor()
    ds = SimpleNamespace(name="fake", _uid=0, connector_policy=None,
                         persistent_id="fake")
    session = Session()
    entry = sup.add_source(None, ds, session, session)
    proxy = _SupervisedSession(entry, session, 0)
    entry.last_activity = sentinel = -1.0
    proxy.detached = True
    proxy.push("k", ("r",), 1)
    proxy.sleep(0)
    assert entry.last_activity == sentinel  # no touch once detached
    assert entry.forwarded == 0
    assert session.drain() == []  # and nothing delivered


def test_session_records_close_reason():
    from pathway_tpu.io._datasource import Session

    s = Session()
    boom = ValueError("x")
    s.close(reason="error", error=boom)
    s.close()  # later clean close must not launder the error
    assert s.closed_reason == "error"
    assert s.error is boom


def test_collect_session_sleep_honors_stop():
    from pathway_tpu.io._datasource import CollectSession

    cs = CollectSession()
    assert cs.sleep(0.01) is True  # no stop requested: keep running
    cs.stopping.set()
    t0 = time.monotonic()
    assert cs.sleep(30.0) is False  # returns immediately, signalling exit
    assert time.monotonic() - t0 < 1.0
    assert cs.stop_requested


# ---------------------------------------------------------------------------
# pipelined execution (PATHWAY_DEVICE_INFLIGHT >= 2) under injected faults
# ---------------------------------------------------------------------------

def _run_counts_with_device_leg(subject, *, inflight, monkeypatch,
                                backend=None, policy=None, **run_kwargs):
    """_run_counts with a traceable device UDF in the pipeline, so the
    groupby/subscribe chain rides the scheduler's deferred device leg."""
    import numpy as np

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", str(inflight))
    G.clear()

    @pw.udf(batch=True, device=True, deterministic=True, return_type=int)
    def dev_len(ws):
        import jax.numpy as jnp

        arr = jnp.asarray(np.asarray([len(w) for w in ws], np.int32))
        return [int(v) for v in np.asarray(arr)]

    t = pw.io.python.read(
        subject, schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=10, persistent_id="pipelined-words",
        connector_policy=policy)
    t = t.select(word=t.word, wl=dev_len(t.word))
    counts = t.groupby(t.word).reduce(word=t.word, c=pw.reducers.count())
    state: dict[str, int] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["word"]] = row["c"]
        elif state.get(row["word"]) == row["c"]:
            del state[row["word"]]

    pw.io.subscribe(counts, on_change)
    cfg = None
    if backend is not None:
        cfg = pw.persistence.Config.simple_config(backend)
    pw.run(persistence_config=cfg, **run_kwargs)
    return state


@pytest.mark.parametrize("inflight", [1, 2])
def test_pipelined_crash_restart_exactly_once_byte_identical(
        inflight, monkeypatch):
    """The PR 3 exactly-once contract is unchanged by pipelining: crash →
    backoff restart → replay produces the identical serialized state at
    every in-flight window (persistence commits barrier on device legs)."""
    baseline = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=inflight, monkeypatch=monkeypatch)
    assert baseline == {"a": 3, "b": 2, "c": 1}
    backend = pw.persistence.Backend.mock()
    subject = flaky_subject(_rows(WORDS), fail_after=3, fail_attempts=2)
    state = _run_counts_with_device_leg(
        subject, inflight=inflight, monkeypatch=monkeypatch,
        backend=backend, policy=_fast_policy())
    assert type(subject).attempts == 3
    assert json.dumps(sorted(state.items())).encode() \
        == json.dumps(sorted(baseline.items())).encode()
    replay = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=inflight, monkeypatch=monkeypatch, backend=backend)
    assert replay == baseline


def test_supervisor_summary_reports_last_restart_age():
    """The connector panel shows WHEN a source last restarted, not just
    how many times (a restart storm and one old restart read the same in
    a bare count)."""
    G.clear()
    schema = pw.schema_from_types(word=str)
    t = pw.io.python.read(
        flaky_subject(_rows(WORDS), fail_after=3, fail_attempts=1),
        schema=schema, autocommit_duration_ms=10, persistent_id="aged",
        connector_policy=_fast_policy())
    pw.io.subscribe(t, lambda *a, **k: None)
    rt = _build_streaming_runtime()
    rt.run()
    s = rt.supervisor.summary()[0]
    assert s["restarts"] == 1
    assert s["last_restart_age_s"] is not None
    assert 0.0 <= s["last_restart_age_s"] < 60.0
    # a source that never restarted reports None, not 0
    G.clear()
    t2 = pw.io.python.read(
        flaky_subject(_rows(["x"]), fail_after=0, fail_attempts=0),
        schema=schema, autocommit_duration_ms=10, persistent_id="calm")
    pw.io.subscribe(t2, lambda *a, **k: None)
    rt2 = _build_streaming_runtime()
    rt2.run()
    assert rt2.supervisor.summary()[0]["last_restart_age_s"] is None


def test_stalled_error_carries_flight_recorder_tail(monkeypatch):
    """With the recorder on, a watchdog escalation's ConnectorStalledError
    — and its ErrorLog entry — embed the flight-recorder tail, so the
    failure names what the engine was executing, not just the source."""
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "1")
    n_before = len(pw.global_error_log().connector_failures())
    subject = hanging_subject(_rows(["a"]))
    with pytest.raises(pw.ConnectorStalledError) as exc_info:
        _run_counts(
            subject, policy=pw.ConnectorPolicy(max_retries=0),
            terminate_on_error=True,
            watchdog=pw.WatchdogConfig(reader_stall_timeout_s=0.3,
                                       tick_deadline_s=None,
                                       poll_interval_s=0.05))
    msg = str(exc_info.value)
    assert "claiming liveness" in msg
    assert "flight recorder tail" in msg
    assert "tick" in msg  # actual span lines, not just the header
    failures = pw.global_error_log().connector_failures()[n_before:]
    assert any("flight recorder tail" in f["message"] for f in failures)


def test_stalled_error_plain_when_recorder_off(monkeypatch):
    monkeypatch.delenv("PATHWAY_FLIGHT_RECORDER", raising=False)
    monkeypatch.delenv("PATHWAY_TRACE_PATH", raising=False)
    subject = hanging_subject(_rows(["a"]))
    with pytest.raises(pw.ConnectorStalledError) as exc_info:
        _run_counts(
            subject, policy=pw.ConnectorPolicy(max_retries=0),
            terminate_on_error=True,
            watchdog=pw.WatchdogConfig(reader_stall_timeout_s=0.3,
                                       tick_deadline_s=None,
                                       poll_interval_s=0.05))
    assert "flight recorder tail" not in str(exc_info.value)


def test_device_bridge_poison_note_carries_tail(monkeypatch):
    """A device-leg failure re-raised on the host thread carries the
    flight-recorder tail as a PEP 678 note: the poisoned tick, its
    operators, and the failing leg are named in the traceback."""
    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", "2")
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "1")
    G.clear()

    @pw.udf(batch=True, device=True, deterministic=True, return_type=int)
    def dev_len(ws):
        return [len(w) for w in ws]

    class _OneRow(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(word="x")

    t = pw.io.python.read(_OneRow(), schema=pw.schema_from_types(word=str),
                          autocommit_duration_ms=10)
    t = t.select(word=t.word, wl=dev_len(t.word))

    def exploding_sink(*a, **k):
        raise RuntimeError("sink exploded on the device leg")

    pw.io.subscribe(t, exploding_sink)
    with pytest.raises(RuntimeError, match="sink exploded") as exc_info:
        pw.run()
    notes = "\n".join(getattr(exc_info.value, "__notes__", []))
    assert "device leg poisoned at tick" in notes
    assert "flight recorder tail" in notes


def test_pipelined_watchdog_restart_with_device_leg(monkeypatch):
    """Watchdog abandon+restart while the pipeline routinely has a device
    leg in flight: the stall verdict comes from reader liveness, never
    from bridge occupancy, and recovery stays exactly-once."""
    subject = hanging_subject(_rows(WORDS), hang_attempts=1)
    state = _run_counts_with_device_leg(
        subject, inflight=2, monkeypatch=monkeypatch,
        policy=_fast_policy(max_retries=2),
        watchdog=pw.WatchdogConfig(reader_stall_timeout_s=0.25,
                                   tick_deadline_s=None,
                                   poll_interval_s=0.05))
    assert state == {"a": 3, "b": 2, "c": 1}
    assert type(subject).attempts == 2


# ---------------------------------------------------------------------------
# watermark durability: resolved-prefix commits (PR 8)
# ---------------------------------------------------------------------------

def test_bridge_watermark_monotone_and_freezes_on_failure():
    """The resolved watermark is the tick of the last cleanly-retired leg
    (FIFO => strictly tick-ordered), and a failed leg freezes it — the
    failed tick never enters the durable prefix."""
    from pathway_tpu.engine.device_bridge import DeviceBridge

    bridge = DeviceBridge(max_inflight=4)
    try:
        assert bridge.resolved_watermark() == 0
        bridge.submit(1, lambda: None)
        bridge.submit(2, lambda: None)
        bridge.barrier()
        assert bridge.resolved_watermark() == 2

        def boom():
            raise RuntimeError("leg failed")

        bridge.submit(3, boom)
        with pytest.raises(RuntimeError, match="leg failed"):
            bridge.barrier()
        assert bridge.resolved_watermark() == 2  # frozen, not advanced
        assert bridge.stats()["resolved_watermark"] == 2
    finally:
        bridge.close()


def test_bridge_watermark_advance_fires_listener():
    """Every advance fires on_advance with the new tick — the hook the
    runtime stamps watchdog progress through."""
    from pathway_tpu.engine.device_bridge import DeviceBridge

    bridge = DeviceBridge(max_inflight=4)
    seen: list[int] = []
    bridge.on_advance = seen.append
    try:
        for t in (1, 2, 3):
            bridge.submit(t, lambda: None)
        bridge.barrier()
        assert seen == [1, 2, 3]
    finally:
        bridge.close()


def test_watermark_advance_stamps_commit_loop_progress():
    """The runtime's watermark listener refreshes last_tick_at, so a
    commit loop blocked behind a full in-flight window reads as
    progressing while legs keep resolving."""
    G.clear()
    t = pw.io.python.read(
        flaky_subject(_rows(["x"]), fail_after=0, fail_attempts=0),
        schema=pw.schema_from_types(word=str), autocommit_duration_ms=10,
        persistent_id="stamp")
    pw.io.subscribe(t, lambda *a, **k: None)
    rt = _build_streaming_runtime()
    stale = rt.last_tick_at - 1000.0
    rt.last_tick_at = stale
    rt._on_watermark_advance(7)
    assert rt.last_tick_at > stale
    rt.run()  # drain cleanly so the fixture's thread-leak check passes


def test_recording_session_seals_partition_pending_prefix():
    """seal(tick) freezes 'everything pushed so far belongs to this
    tick's drain'; take_sealed(watermark) removes exactly the prefix
    under seals <= watermark, leaving later and unsealed entries."""
    from pathway_tpu.engine.persistence import _RecordingSession
    from pathway_tpu.io._datasource import Session

    rec = _RecordingSession(Session(), skip=0)
    rec.push("k1", ("a",), 1)
    rec.push("k2", ("b",), 1)
    rec.seal(1)
    rec.push("k3", ("c",), 1)
    rec.seal(2)
    rec.push("k4", ("d",), 1)  # pushed after the last seal
    assert rec.take_sealed(0) == []
    assert [e[0] for e in rec.take_sealed(1)] == ["k1", "k2"]
    assert [e[0] for e in rec.take_sealed(99)] == ["k3"]  # k4 unsealed
    rec.seal(100)
    assert [e[0] for e in rec.take_sealed(100)] == ["k4"]
    assert rec.pending == []


def test_commit_records_carry_watermark_tick():
    """A watermark commit appends exactly the sealed-:math:`\\le`-watermark
    prefix in a record stamped with the WATERMARK tick, and the stats
    snapshot reports the lag + bridge depth at commit."""
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.io._datasource import CallbackSource, Session

    backend = pw.persistence.Backend.mock()
    cfg = pw.persistence.Config.simple_config(backend)
    driver = PersistenceDriver(cfg)
    src = CallbackSource(lambda: iter(()), pw.schema_from_types(x=int))
    src.persistent_id = "wm"
    rec = driver.attach_source(src, Session())
    rec.push("k1", (1,), 1)
    driver.seal(3)
    rec.push("k2", (2,), 1)
    driver.seal(4)
    driver.commit(5, watermark=3, inflight=2)
    assert backend._mock_store["wm"] == [(3, [("k1", (1,), 1, None)])]
    st = driver.stats()
    assert st["watermark"] == 3
    assert st["lag_ticks"] == 2  # tick 5 committed only up to 3
    assert st["inflight_at_commit"] == 2
    assert st["commits"] == 1 and st["commits_with_data"] == 1
    # restart replays exactly the committed watermark
    assert PersistenceDriver(cfg).restore_time() == 3
    # a later commit whose watermark caught up takes the rest
    driver.commit(6, watermark=6)
    assert [t for t, _ in backend._mock_store["wm"]] == [3, 6]
    assert backend._mock_store["wm"][1][1][0][0] == "k2"


def _run_counts_slow_device(subject, *, inflight, monkeypatch, backend,
                            leg_sleep_s=0.05, **run_kwargs):
    """_run_counts with a device UDF that sleeps per non-empty batch, and
    the built runtime returned for post-run inspection."""
    import numpy as np

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", str(inflight))
    G.clear()

    @pw.udf(batch=True, device=True, deterministic=True, return_type=int)
    def dev_len(ws):
        import jax.numpy as jnp

        time.sleep(leg_sleep_s)
        arr = jnp.asarray(np.asarray([len(w) for w in ws], np.int32))
        return [int(v) for v in np.asarray(arr)]

    t = pw.io.python.read(
        subject, schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=10, persistent_id="slow-dev")
    t = t.select(word=t.word, wl=dev_len(t.word))
    counts = t.groupby(t.word).reduce(word=t.word, c=pw.reducers.count())
    state: dict[str, int] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["word"]] = row["c"]
        elif state.get(row["word"]) == row["c"]:
            del state[row["word"]]

    pw.io.subscribe(counts, on_change)
    rt = _build_streaming_runtime(
        persistence_config=pw.persistence.Config.simple_config(backend),
        **run_kwargs)
    rt.run()
    return state, rt


def test_commit_no_longer_barriers_bridge(monkeypatch):
    """THE acceptance property of the watermark refactor: with
    persistence ON, the bridge still reaches depth > 1 (the old
    barrier-before-commit forced effective depth 1) and trailing commits
    happen while legs are in flight — checkpoint cadence decoupled from
    PATHWAY_DEVICE_INFLIGHT."""
    words = [f"w{i % 3}" for i in range(10)]
    backend = pw.persistence.Backend.mock()
    state, rt = _run_counts_slow_device(
        flaky_subject(_rows(words), fail_after=0, fail_attempts=0,
                      delay_s=0.01),
        inflight=4, monkeypatch=monkeypatch, backend=backend)
    assert state == {"w0": 4, "w1": 3, "w2": 3}
    stats = rt.scheduler.bridge_stats()
    assert stats is not None and stats["max_depth"] >= 2, stats
    pst = rt.persistence.stats()
    # trailing commits: at least one durable commit happened BEFORE the
    # end-of-stream flush (which would be the single commit under a
    # drain-the-bridge design with this pacing)
    assert pst["commits_with_data"] >= 1
    assert pst["watermark"] >= 1
    # and the run is fully durable at the end: a fresh process replays
    # to the identical state
    G.clear()
    replay = _run_counts(flaky_subject(_rows(words), fail_after=0,
                                       fail_attempts=0), backend=backend,
                         persistent_id="slow-dev")
    assert replay == state


# every new watermark boundary x in-flight depth; persistence.* points
# disable write retries so the injected failure actually crashes the run
_SWEEP_POINTS = ("bridge.leg.exec", "bridge.leg.resolved",
                 "persistence.commit", "persistence.append.torn",
                 "persistence.fsync")


@pytest.mark.parametrize("inflight", [1, 2, 4])
@pytest.mark.parametrize("point", _SWEEP_POINTS)
def test_crash_sweep_byte_identical_exactly_once(point, inflight,
                                                 monkeypatch, tmp_path):
    """Crash-at-every-fault-point sweep: a run killed at any watermark
    boundary, at any in-flight depth, must recover on rerun to output
    byte-identical to the synchronous no-fault run. (At inflight=1 the
    bridge.* points never arm — the run completes; the assertion still
    pins sync equivalence.) Filesystem backend: the persistence.* points
    live inside the real file log's append."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "0")
    baseline = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=1, monkeypatch=monkeypatch)
    assert baseline == {"a": 3, "b": 2, "c": 1}
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    # seeded crash position (process-stable, unlike hash()): vary the hit
    # index per case so the sweep lands on different committed-prefix
    # lengths
    k = 1 + (len(point) + inflight) % 3
    with faults.arm(point, faults.FailOnHit(k)):
        try:
            _run_counts_with_device_leg(
                flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                              delay_s=0.02),
                inflight=inflight, monkeypatch=monkeypatch,
                backend=backend, terminate_on_error=True)
        except InjectedFault:
            pass  # the crash: frozen watermark, torn tail, or lost fsync
    faults.reset()
    state = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=inflight, monkeypatch=monkeypatch, backend=backend)
    assert json.dumps(sorted(state.items())).encode() \
        == json.dumps(sorted(baseline.items())).encode()


def test_double_crash_replay_at_watermark_boundary(monkeypatch):
    """Crash-of-a-recovery at the watermark boundary: two consecutive
    device-leg crashes (each freezing a different watermark), then a
    clean run — replay+skip must hold across both committed prefixes."""
    baseline = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=1, monkeypatch=monkeypatch)
    backend = pw.persistence.Backend.mock()
    for k in (2, 3):  # second crash strictly later in the leg sequence
        with faults.arm("bridge.leg.exec", faults.FailOnHit(k)):
            try:
                _run_counts_with_device_leg(
                    flaky_subject(_rows(WORDS), fail_after=0,
                                  fail_attempts=0, delay_s=0.02),
                    inflight=4, monkeypatch=monkeypatch, backend=backend,
                    terminate_on_error=True)
            except InjectedFault:
                pass
        faults.reset()
    state = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=4, monkeypatch=monkeypatch, backend=backend)
    assert json.dumps(sorted(state.items())).encode() \
        == json.dumps(sorted(baseline.items())).encode()


def test_restart_after_poisoned_bridge_resumes_from_watermark(monkeypatch):
    """A poisoned bridge freezes the watermark; the teardown path still
    commits the resolved prefix, and the restart replays it (restore
    time == frozen watermark) instead of starting from zero."""
    backend = pw.persistence.Backend.mock()

    class _PoisonAfterFirstCommit:
        """Fail the first device leg dispatched after a durable record
        exists — deterministic 'N committed + M in flight' shape without
        racing tick pacing."""

        def __call__(self, point, ctx):
            if backend._mock_store.get("pipelined-words"):
                raise InjectedFault(f"poison at {point!r} after commit")

    with faults.arm("bridge.leg.exec", _PoisonAfterFirstCommit()):
        with pytest.raises(InjectedFault):
            _run_counts_with_device_leg(
                flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                              delay_s=0.03),
                inflight=4, monkeypatch=monkeypatch, backend=backend,
                terminate_on_error=True)
    faults.reset()
    # the resolved prefix was committed before escalation
    committed = backend._mock_store.get("pipelined-words", [])
    assert committed, "poisoned run committed no resolved prefix"
    from pathway_tpu.engine.persistence import PersistenceDriver

    frozen = PersistenceDriver(
        pw.persistence.Config.simple_config(backend)).restore_time()
    assert frozen >= 1
    assert all(t <= frozen for t, _ in committed)
    state = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=4, monkeypatch=monkeypatch, backend=backend)
    assert state == {"a": 3, "b": 2, "c": 1}


def test_device_leg_failure_degrades_when_not_terminating(monkeypatch):
    """terminate_on_error=False on a device-leg failure: the run absorbs
    the poison after committing the resolved prefix — recorded in the
    ErrorLog (kind='engine'), flagged on the supervisor (healthz reads
    degraded), never laundered into a clean healthy shutdown."""
    import numpy as np  # noqa: F401 — device UDF path

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", "2")
    G.clear()

    @pw.udf(batch=True, device=True, deterministic=True, return_type=int)
    def dev_len(ws):
        return [len(w) for w in ws]

    t = pw.io.python.read(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                      delay_s=0.02),
        schema=pw.schema_from_types(word=str), autocommit_duration_ms=10,
        persistent_id="degrade")
    t = t.select(word=t.word, wl=dev_len(t.word))
    pw.io.subscribe(t, lambda *a, **k: None)
    backend = pw.persistence.Backend.mock()
    rt = _build_streaming_runtime(
        terminate_on_error=False,
        persistence_config=pw.persistence.Config.simple_config(backend))
    n_before = len([e for e in pw.global_error_log().entries
                    if e["kind"] == "engine"])
    with faults.arm("bridge.leg.exec", faults.FailOnHit(2)):
        rt.run()  # absorbed: no raise
    faults.reset()
    assert rt.supervisor.engine_failed
    assert not rt.supervisor.healthy()
    from pathway_tpu.engine.http_server import MonitoringHttpServer

    healthy, body = MonitoringHttpServer(rt, port=0).healthz_payload()
    assert not healthy and body["engine_failed"]
    engine_entries = [e for e in pw.global_error_log().entries
                      if e["kind"] == "engine"][n_before:]
    assert any("device leg" in e["message"] for e in engine_entries)


def test_transient_fsync_failure_retried_run_completes(tmp_path,
                                                       monkeypatch):
    """A transient fsync failure is retried with backoff instead of
    killing the run; the output and the durable log are intact."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", "1")
    from pathway_tpu.engine.persistence import write_retries_total

    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    before = write_retries_total()
    with faults.arm("persistence.fsync", faults.FailNTimes(2)):
        state = _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                          fail_attempts=0),
                            backend=backend)
    faults.reset()
    assert state == {"a": 3, "b": 2, "c": 1}
    assert write_retries_total() - before >= 2
    replay = _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                       fail_attempts=0), backend=backend)
    assert replay == state


def test_transient_torn_append_retried_repairs_tail(tmp_path, monkeypatch):
    """A torn append (header written, payload lost) that is retried must
    truncate the torn bytes first — the log stays fully readable and the
    restart replays exactly-once."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", "1")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    with faults.arm("persistence.append.torn", faults.FailNTimes(1)):
        state = _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                          fail_attempts=0),
                            backend=backend)
    faults.reset()
    assert state == {"a": 3, "b": 2, "c": 1}
    from pathway_tpu.engine.persistence import SnapshotLog

    path = str(tmp_path / "pstate" / "streams" / "words.snap")
    records = SnapshotLog(path).read_all()
    assert sum(len(e) for _t, e in records) == len(WORDS)
    replay = _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                       fail_attempts=0), backend=backend)
    assert replay == state


def test_persistence_retry_exhaustion_escalates(monkeypatch, tmp_path):
    """Write retries exhausted escalate per terminate_on_error=True: the
    backend's own exception reaches pw.run's caller."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "1")
    monkeypatch.setenv("PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", "1")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    with faults.arm("persistence.fsync", faults.FailNTimes(50)):
        with pytest.raises(InjectedFault):
            _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                      fail_attempts=0, delay_s=0.02),
                        backend=backend, terminate_on_error=True)
    faults.reset()


def test_persistence_retry_exhaustion_degrades_when_not_terminating(
        monkeypatch, tmp_path):
    """...and per terminate_on_error=False: absorbed, recorded in the
    ErrorLog, run ends cleanly."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "0")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstate"))
    n_before = len([e for e in pw.global_error_log().entries
                    if e["kind"] == "engine"])
    with faults.arm("persistence.fsync", faults.FailNTimes(50)):
        _run_counts(flaky_subject(_rows(WORDS), fail_after=0,
                                  fail_attempts=0, delay_s=0.02),
                    backend=backend, terminate_on_error=False)
    faults.reset()
    engine_entries = [e for e in pw.global_error_log().entries
                      if e["kind"] == "engine"][n_before:]
    assert engine_entries, "exhausted retries left no ErrorLog entry"


def test_commit_stall_postmortem_names_oldest_leg(caplog):
    """A genuine commit-loop breach names the oldest unresolved device
    leg (tick + seconds in flight) — bridge_inflight() survives
    recording-off, so the attribution never depends on the recorder."""
    import logging
    from types import SimpleNamespace

    from pathway_tpu.engine.device_bridge import DeviceBridge
    from pathway_tpu.engine.supervisor import (ConnectorSupervisor,
                                               Watchdog, WatchdogConfig)

    bridge = DeviceBridge(max_inflight=2)
    release = threading.Event()
    bridge.submit(7, release.wait)
    try:
        deadline = time.monotonic() + 5
        while bridge.inflight() is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert bridge.inflight() is not None

        class _Sched:
            recorder = None

            @staticmethod
            def bridge_inflight():
                return bridge.inflight()

        runtime = SimpleNamespace(scheduler=_Sched(),
                                  last_tick_at=time.monotonic() - 100.0)
        sup = ConnectorSupervisor()
        wd = Watchdog(runtime, sup, WatchdogConfig(tick_deadline_s=1.0))
        with caplog.at_level(logging.ERROR,
                             logger="pathway_tpu.engine.supervisor"):
            wd._check_commit_loop(time.monotonic())
        assert sup.commit_stalled
        assert wd.commit_stall_events == 1
        assert "oldest unresolved device leg: tick 7" in caplog.text
    finally:
        release.set()
        bridge.close()


def test_slow_but_advancing_watermark_never_trips_watchdog(monkeypatch):
    """A commit loop waiting on a full in-flight window of slow-but-
    advancing device legs (including the end-of-stream barrier over the
    queued backlog) stays under the tick deadline because every resolved
    leg stamps progress — zero commit-stall breaches."""
    words = [f"w{i % 3}" for i in range(10)]
    backend = pw.persistence.Backend.mock()
    state, rt = _run_counts_slow_device(
        flaky_subject(_rows(words), fail_after=0, fail_attempts=0,
                      delay_s=0.015),
        inflight=4, monkeypatch=monkeypatch, backend=backend,
        leg_sleep_s=0.15,
        watchdog=pw.WatchdogConfig(tick_deadline_s=1.0,
                                   reader_stall_timeout_s=None,
                                   poll_interval_s=0.05))
    assert state == {"w0": 4, "w1": 3, "w2": 3}
    assert rt.watchdog.commit_stall_events == 0
    assert not rt.supervisor.commit_stalled
