"""stdlib completion: sorting helpers, all-rows applies, louvain
(reference: stdlib/indexing/sorting.py, stdlib/utils/col.py,
stdlib/graphs/louvain_communities/impl.py)."""

import pathway_tpu as pw
from pathway_tpu.stdlib.graphs import exact_modularity, louvain_communities
from pathway_tpu.stdlib.indexing import (
    build_sorted_index,
    filter_smallest_k,
    retrieve_prev_next_values,
)
from pathway_tpu.stdlib.utils.col import apply_all_rows, multiapply_all_rows
from tests.utils import T, rows_of


def test_retrieve_prev_next_values_skips_nones():
    t = T("""
    k | value
    1 | 10
    2 |
    3 |
    4 | 40
    5 |
    """)
    ordered = t.sort(t.k)
    merged = ordered.select(prev=ordered.prev, next=ordered.next,
                            value=t.restrict(ordered).value)
    res = retrieve_prev_next_values(merged)
    # map pointers back to the value they point at
    vals = res.select(
        pv=t.ix(res.prev_value, optional=True, context=res).value,
        nv=t.ix(res.next_value, optional=True, context=res).value,
    )
    joined = vals.select(k=t.restrict(vals).k, pv=vals.pv, nv=vals.nv)
    got = {k: (pv, nv) for k, pv, nv in rows_of(joined)}
    assert got[1] == (None, 40)   # no earlier value; next non-None is 40
    assert got[2] == (10, 40)
    assert got[3] == (10, 40)
    assert got[5] == (40, None)


def test_build_sorted_index_shape():
    t = T("""
    key | instance
    5   | 0
    1   | 0
    3   | 0
    """)
    idx = build_sorted_index(t)
    assert set(idx.keys()) == {"index", "oracle"}
    [(inst, root)] = rows_of(idx["oracle"])
    assert inst == 0


def test_filter_smallest_k():
    t = T("""
    v  | inst
    10 | a
    5  | a
    7  | a
    1  | b
    2  | b
    """)
    ks = T("""
    instance | k
    a        | 2
    b        | 1
    """)
    res = filter_smallest_k(t.v, t.inst, ks)
    assert sorted(rows_of(res)) == [(1, "b"), (5, "a"), (7, "a")]


def test_apply_all_rows():
    t = T("""
    a | b
    1 | 10
    2 | 20
    3 | 30
    """)
    res = apply_all_rows(
        t.a, t.b, fun=lambda ca, cb: [x + sum(ca) + sum(cb)
                                      for x in ca],
        result_col_name="res")
    assert sorted(rows_of(res)) == [(67,), (68,), (69,)]
    multi = multiapply_all_rows(
        t.a, t.b,
        fun=lambda ca, cb: ([x + 1 for x in ca], [y - 1 for y in cb]),
        result_col_names=["a1", "b1"])
    assert sorted(rows_of(multi)) == [(2, 9), (3, 19), (4, 29)]


def test_louvain_two_cliques():
    # two triangles connected by a single weak edge → two communities
    edges_raw = T("""
    su | sv
    a  | b
    b  | c
    c  | a
    d  | e
    e  | f
    f  | d
    a  | d
    """)
    verts = T("""
    name
    a
    b
    c
    d
    e
    f
    """).with_id_from(pw.this.name)
    fwd = edges_raw.select(u=verts.pointer_from(edges_raw.su),
                           v=verts.pointer_from(edges_raw.sv))
    bwd = edges_raw.select(u=verts.pointer_from(edges_raw.sv),
                           v=verts.pointer_from(edges_raw.su))
    edges = fwd.concat_reindex(bwd)
    clusters = louvain_communities(verts, edges)
    labeled = clusters.select(name=verts.restrict(clusters).name,
                              c=pw.apply(int, clusters.c))
    got = dict(rows_of(labeled))
    assert got["a"] == got["b"] == got["c"]
    assert got["d"] == got["e"] == got["f"]
    assert got["a"] != got["d"]
    [(q,)] = rows_of(exact_modularity(edges, clusters))
    assert q > 0.3  # two-clique partition is strongly modular


def _sym_edges(pairs):
    """names -> (verts, edges) tables with both edge directions."""
    names = sorted({n for p in pairs for n in p})
    verts = T("name\n" + "\n".join(names)).with_id_from(pw.this.name)
    raw = T("su | sv\n" + "\n".join(f"{a} | {b}" for a, b in pairs))
    fwd = raw.select(u=verts.pointer_from(raw.su),
                     v=verts.pointer_from(raw.sv))
    bwd = raw.select(u=verts.pointer_from(raw.sv),
                     v=verts.pointer_from(raw.su))
    return names, verts, fwd.concat_reindex(bwd)


def _modularity(pairs, labels):
    """Exact Q over the directed-doubled graph, computed independently."""
    dedges = [(a, b) for a, b in pairs] + [(b, a) for a, b in pairs]
    m2 = len(dedges)
    deg = {}
    for a, _ in dedges:
        deg[a] = deg.get(a, 0) + 1
    q = 0.0
    for c in set(labels.values()):
        members = {n for n, l in labels.items() if l == c}
        w_in = sum(1 for a, b in dedges if a in members and b in members)
        dc = sum(deg.get(n, 0) for n in members)
        q += w_in / m2 - (dc / m2) ** 2
    return q


def test_louvain_gain_is_locally_optimal():
    # Regression for the deg(v) stay/move correction (reference
    # louvain_communities/impl.py:111-145): the result must be a
    # 1-move-local optimum of exact modularity — the uncorrected gain
    # (w - deg(v)*deg(C)/2m, no stay candidate) accepts degrading moves.
    pairs = [
        # 4-clique A
        ("a1", "a2"), ("a1", "a3"), ("a1", "a4"),
        ("a2", "a3"), ("a2", "a4"), ("a3", "a4"),
        # 4-clique B
        ("b1", "b2"), ("b1", "b3"), ("b1", "b4"),
        ("b2", "b3"), ("b2", "b4"), ("b3", "b4"),
        # inter-clique noise + a bridge vertex leaning toward A
        ("a1", "b1"), ("a2", "b2"),
        ("g", "a3"), ("g", "a4"), ("g", "b3"),
    ]
    names, verts, edges = _sym_edges(pairs)
    clusters = louvain_communities(verts, edges, iterations=40)
    labeled = clusters.select(name=verts.restrict(clusters).name,
                              c=pw.apply(int, clusters.c))
    labels = dict(rows_of(labeled))
    q = _modularity(pairs, labels)
    [(q_engine,)] = rows_of(exact_modularity(edges, clusters))
    assert abs(q - q_engine) < 1e-9
    # no single-vertex move (to any adjacent cluster or a fresh singleton)
    # may improve modularity
    fresh = object()
    for v in names:
        for target in set(labels.values()) | {fresh}:
            if target == labels[v]:
                continue
            moved = dict(labels)
            moved[v] = target
            assert _modularity(pairs, moved) <= q + 1e-9, (
                f"moving {v} improves modularity: "
                f"{_modularity(pairs, moved)} > {q}")
