"""Semantic result-cache canary (engine/result_cache.py), on the REAL
``examples/streaming_etl.py`` graph: a vector KNN serving route is
mounted next to the example's own order/category pipeline, and the same
deterministic query/churn script runs twice — cache-off then cache-on.
Gates:

1. **byte-identity** — the cache-on run's response bodies are
   byte-for-byte identical to the cache-off run's, across a churn step
   that provably CHANGES answers (so identity is not vacuous: a stale
   serve would diverge here);
2. **hit-rate > 0** — the repeated query pool actually hits (the cache
   is live, not configured-but-inert), and the churn step actually
   invalidates (the incremental invalidator saw the deltas).

Exits 0 iff both hold. Run: ``python tests/semantic_cache_canary.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

DIM = 8
N_SEED = 64
N_CHURN = 16
POOL = 6
REPEATS = 3
K = 3


def _serving_run(cache_on: bool) -> tuple[list[bytes], dict | None]:
    """One full serving run: streaming_etl + KNN route, seeded load →
    query script → churn → same query script. Returns the raw response
    bodies in request order plus the operator cache stats (None when
    the cache is disabled)."""
    os.environ["PATHWAY_RESULT_CACHE"] = "1" if cache_on else "0"
    from tests.pipelining_canary import _write_feed

    import pathway_tpu as pw
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.engine.result_cache import live_cache_stats
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.io.http import PathwayWebserver, rest_connector
    from pathway_tpu.io.python import ConnectorSubject
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index,
    )

    G.clear()
    rng = np.random.default_rng(5)
    seed_vecs = rng.random((N_SEED, DIM), np.float32) * 2 - 1
    pool = rng.random((POOL, DIM), np.float32) * 2 - 1
    # churn vectors sit ON the query pool (plus noise), so post-churn
    # answers provably change — byte-identity across the churn step is
    # the no-stale-serve proof, not a trivial replay
    churn_vecs = (pool[np.arange(N_CHURN) % POOL]
                  + rng.random((N_CHURN, DIM), np.float32) * 0.01)
    loaded = threading.Event()
    churn_go = threading.Event()

    class Vecs(ConnectorSubject):
        def run(self):
            for v in seed_vecs:
                self.next(v=v)
            loaded.set()
            while not churn_go.is_set():
                if not self._session.sleep(0.02):
                    return
            for v in churn_vecs:
                self.next(v=v)

    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        orders_dir, cats_csv = _write_feed(root)
        from examples.streaming_etl import build

        build(orders_dir, cats_csv, str(root / "out.csv"))
        data = pw.io.python.read(
            Vecs(), schema=sch.schema_from_types(v=np.ndarray),
            autocommit_duration_ms=20, name="cache_canary_vecs")
        index = default_brute_force_knn_document_index(
            data.v, data, dimensions=DIM, reserved_space=48)  # forces grow
        ws = PathwayWebserver(host="127.0.0.1", port=0)
        qschema = sch.schema_from_types(vec=dt.ANY, k=int)
        queries, writer = rest_connector(
            webserver=ws, route="/knn", schema=qschema, methods=("POST",),
            delete_completed_queries=True, autocommit_duration_ms=10)
        qv = queries.select(
            qv=pw.apply(lambda v: np.asarray(v, dtype=np.float32),
                        queries.vec),
            k=queries.k)
        res = index.query_as_of_now(qv.qv, number_of_matches=qv.k)
        writer(res.select(
            scores=pw.apply(lambda ds: [float(d) for d in ds],
                            res._pw_index_reply_score)))

        errors: list[BaseException] = []

        def _run():
            try:
                pw.run()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        th = threading.Thread(target=_run, daemon=True,
                              name=f"cache-canary-{cache_on}")
        th.start()
        bodies: list[bytes] = []
        stats = None
        try:
            deadline = time.monotonic() + 120.0
            rt = None
            while time.monotonic() < deadline and rt is None:
                live = list(_streaming._ACTIVE_RUNTIMES)
                if live and ws._started.is_set() and ws.port:
                    rt = live[0]
                if errors:
                    raise errors[0]
                time.sleep(0.05)
            assert rt is not None, "runtime never started"
            assert loaded.wait(60.0), "seed vectors never loaded"

            def rows_ingested() -> int:
                return sum(
                    st.get("insertions", 0)
                    for nid, st in rt.scheduler.stats.items()
                    if rt.runner.graph.nodes[nid].name
                    == "cache_canary_vecs")

            def wait_rows(n: int):
                dl = time.monotonic() + 60.0
                while time.monotonic() < dl:
                    if rows_ingested() >= n:
                        return
                    time.sleep(0.02)
                raise TimeoutError(
                    f"ingest stalled at {rows_ingested()}/{n} rows")

            def ask(vec) -> bytes:
                body = json.dumps({"vec": [float(x) for x in vec],
                                   "k": K}).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{ws.port}/knn", data=body,
                    method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return resp.read()

            def query_script():
                # repeats back-to-back AND interleaved: same-tick
                # duplicate misses and later-tick hits both exercise
                for r in range(REPEATS):
                    for i in range(POOL):
                        bodies.append(ask(pool[i]))

            wait_rows(N_SEED)
            query_script()
            churn_go.set()
            wait_rows(N_SEED + N_CHURN)
            query_script()
            stats = live_cache_stats()
        finally:
            churn_go.set()
            _streaming.stop_all()
            th.join(15.0)
            G.clear()
            os.environ.pop("PATHWAY_RESULT_CACHE", None)
        if errors:
            raise errors[0]
    return bodies, stats


def main() -> int:
    off_bodies, off_stats = _serving_run(cache_on=False)
    if off_stats is not None:
        print("FAIL: cache-off run still registered a live cache",
              file=sys.stderr)
        return 1
    on_bodies, on_stats = _serving_run(cache_on=True)
    n = POOL * REPEATS * 2
    if len(off_bodies) != n or len(on_bodies) != n:
        print(f"FAIL: expected {n} responses, got off={len(off_bodies)} "
              f"on={len(on_bodies)}", file=sys.stderr)
        return 1
    if on_bodies != off_bodies:
        diffs = [i for i, (a, b) in enumerate(zip(off_bodies, on_bodies))
                 if a != b]
        print(f"FAIL: cache-on diverged from cache-off at requests "
              f"{diffs[:5]} (of {len(diffs)}): "
              f"off={off_bodies[diffs[0]][:120]!r} "
              f"on={on_bodies[diffs[0]][:120]!r}", file=sys.stderr)
        return 1
    half = POOL * REPEATS
    changed = sum(1 for i in range(half)
                  if off_bodies[i] != off_bodies[half + i])
    if changed == 0:
        print("FAIL: churn step changed no answers — the identity gate "
              "is vacuous", file=sys.stderr)
        return 1
    if on_stats is None:
        print("FAIL: cache-on run registered no live cache",
              file=sys.stderr)
        return 1
    if not on_stats["hits"] > 0:
        print(f"FAIL: cache never hit: {on_stats}", file=sys.stderr)
        return 1
    if not on_stats["invalidations"] > 0:
        print(f"FAIL: churn never invalidated: {on_stats}",
              file=sys.stderr)
        return 1
    print(f"OK: semantic-cache canary holds — {n} responses "
          f"byte-identical across churn ({changed}/{half} answers "
          f"changed), hits={on_stats['hits']} "
          f"misses={on_stats['misses']} "
          f"invalidations={on_stats['invalidations']} "
          f"hit_ratio={on_stats['hit_ratio']:.2f}")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
