"""Auto-jit execution tier (internals/autojit.py).

Contracts under test:

- **byte-identity**: PATHWAY_AUTO_JIT=1 and =0 produce identical captured
  streams across int/float/bool/str/None-able columns, including dirty
  cells (None, bigints past the guard, ERROR-producing rows) and
  data-dependent per-cell errors (negative sqrt, zero divisors);
- **fused-chain vs per-expr equivalence**: a chained composition fuses
  into ONE program with one device dispatch per batch and matches the
  expression-by-expression lowering cell for cell;
- **runtime demotion**: a program whose compiled form fails on real data
  (the untraceable-at-runtime class the AST pass cannot see) demotes
  loudly-once, bumps the counter, and the interpreted fallback keeps the
  output byte-identical; data-dependent FloatingPointError falls back
  per-batch WITHOUT demoting;
- **host/device map split**: a select carrying both fusable chains and
  host-only UDFs lowers to map_host/map_dev/ZipAligned, identical output;
- **warmup**: pw.warmup walks the power-of-two bucket ladder so a
  later run_batch adds no compiles (asserted compile counts);
- satellites: closure-over-module rewrite (import math in an enclosing
  scope), int-overflow proof bars unprovable trees, ZipAligned alignment
  asserts, stats/metrics surfaces.
"""

from __future__ import annotations

import logging
import math

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_rows
from pathway_tpu.internals import autojit
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner


@pytest.fixture(autouse=True)
def _fresh():
    import gc

    G.clear()
    gc.collect()  # drain dead programs out of the weak registry
    autojit.reset_stats()
    yield
    G.clear()
    autojit.reset_stats()


# -- the UDF zoo: one of each class the tier handles ------------------------

@pw.udf
def boost(x: int) -> int:
    return x * 3 + 7


@pw.udf
def gate(y: float) -> float:
    return y if y < 0.75 else 0.75


@pw.udf
def mixf(x: int, y: float) -> float:
    return x * 0.0001 + y * 0.5


@pw.udf
def rootp(y: float) -> float:
    return math.sqrt(y) + 1.0


@pw.udf
def stepi(x: int) -> int:
    return (x % 7) + (x // 3)


@pw.udf
def cube(x: int) -> int:
    return x * x * x  # 93-bit bound: provably unfusable (bigint exact)


@pw.udf(deterministic=True)
def tag(x: int) -> str:
    return f"doc-{x % 97}"


def _run_events(build, jit: str, monkeypatch, min_rows: int | None = None):
    """Captured (key,row,time,diff) events for one mode, plus stats."""
    monkeypatch.setenv("PATHWAY_AUTO_JIT", jit)
    if min_rows is not None:
        monkeypatch.setattr(autojit, "MIN_ROWS", min_rows)
    G.clear()
    autojit.reset_stats()
    out = build()
    runner = GraphRunner()
    cap = runner.capture(out)
    runner.run_batch(n_workers=1)
    stats = autojit.autojit_stats()
    G.clear()
    return list(cap.events), stats


# ---------------------------------------------------------------------------
# byte-identity property suite across dtypes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_identity_across_dtypes(monkeypatch, seed):
    """Randomized int/float/bool/str/Optional[int] columns, clean majority
    plus seeded dirty cells: ON == OFF cell for cell, and the ON run
    genuinely dispatched through the fused tier (non-vacuous)."""
    rng = np.random.default_rng(seed)
    n = 64
    rows = []
    for i in range(n):
        x = int(rng.integers(-10_000, 10_000))
        y = float(rng.random())
        b = bool(rng.integers(0, 2))
        s = f"w{int(rng.integers(0, 9))}"
        oi: int | None = int(rng.integers(0, 100))
        if i % 13 == 5:
            oi = None                     # None-able cell → fallback row
        if i % 17 == 9:
            x = 1 << 40                   # bigint past the 2^31 guard
        rows.append((x, y, b, s, oi, i // 16, 1))
    schema = sch.schema_from_types(x=int, y=float, b=bool, s=str,
                                   oi=int | None)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(
            sb=boost(t.x), sg=gate(t.y), sm=mixf(t.x, t.y),
            sn=rootp(t.y), st=stepi(t.x), sc=cube(t.x),
            tg=tag(t.x), keep=t.b, raw=t.s, opt=t.oi,
            pick=pw.if_else(t.b, t.y, 0.0))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on  # non-vacuous
    assert on_stats["programs"] >= 1
    assert (on_stats["device_dispatches"] + on_stats["vector_dispatches"]) > 0
    assert on_stats["demotions"] == 0


def test_identity_with_per_cell_errors(monkeypatch):
    """Data-dependent per-cell failures (negative sqrt → interpreter
    raises → ERROR cell) fall back per-batch and stay byte-identical —
    the FloatingPointError escape, not a demotion."""
    rows = [(float(i - 6) / 4.0, i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(y=float)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sn=rootp(t.y), sg=gate(t.y))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on
    assert on_stats["demotions"] == 0
    # the first tick carries negative y → that batch fell back whole
    assert on_stats["fallback_batches"] >= 1


def test_identity_small_batches_stay_interpreted(monkeypatch):
    """Batches below MIN_ROWS never dispatch (array setup would cost more
    than it saves) and remain identical."""
    rows = [(i, float(i), i, 1) for i in range(6)]  # 1-row ticks
    schema = sch.schema_from_types(x=int, y=float)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sb=boost(t.x), sg=gate(t.y))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["device_dispatches"] == 0
    assert on_stats["vector_dispatches"] == 0


# ---------------------------------------------------------------------------
# fused-chain vs per-expr equivalence
# ---------------------------------------------------------------------------

def test_fused_chain_matches_per_expr(monkeypatch):
    """A composed chain (UDF-of-UDF args) fuses into ONE program — a
    single dispatch per batch for the whole tree — and matches the
    select-per-stage lowering cell for cell."""
    rows = [(int(i), float(i) / 33.0, i // 32, 1) for i in range(128)]
    schema = sch.schema_from_types(x=int, y=float)

    def build_chain():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(out=mixf(boost(t.x), gate(t.y)), extra=boost(t.x))

    def build_staged():
        t = table_from_rows(schema, list(rows), is_stream=True)
        t1 = t.select(sb=boost(t.x), sg=gate(t.y))
        return t1.select(out=mixf(t1.sb, t1.sg), extra=t1.sb)

    chain_on, chain_stats = _run_events(build_chain, "1", monkeypatch)
    chain_off, _ = _run_events(build_chain, "0", monkeypatch)
    staged_on, staged_stats = _run_events(build_staged, "1", monkeypatch)
    rows_of = lambda evs: sorted(tuple(r) for _, r, _, d in evs if d > 0)  # noqa: E731
    assert rows_of(chain_on) == rows_of(chain_off) == rows_of(staged_on)
    assert chain_stats["programs"] == 1
    # ONE guard pass per tick feeds both partitions: the xla partition
    # (extra=boost) and the numpy partition (out: compounding float
    # arithmetic is statically barred from XLA) each dispatch once
    n_ticks = 4
    assert chain_stats["device_dispatches"] in (0, n_ticks)
    assert (chain_stats["device_dispatches"]
            + chain_stats["vector_dispatches"]) == 2 * n_ticks
    # the staged version fuses each map separately — still identical
    assert staged_stats["programs"] == 2


# ---------------------------------------------------------------------------
# runtime demotion: the safety net for what static analysis cannot see
# ---------------------------------------------------------------------------

def _live_program():
    import gc

    gc.collect()  # only THIS test's runner should hold a live program
    progs = list(autojit._REGISTRY)
    assert len(progs) == 1
    return progs[0]


def test_runtime_demotion_loud_once_and_identical(monkeypatch, caplog):
    """A program whose compiled form fails on real data (data-dependent
    control flow the AST pass admitted) demotes loudly ONCE, bumps the
    counter, and the output is byte-identical to the interpreter."""
    rows = [(int(i), i // 16, 1) for i in range(64)]
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sb=boost(t.x))

    off, _ = _run_events(build, "0", monkeypatch)

    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    G.clear()
    autojit.reset_stats()
    out = build()
    runner = GraphRunner()
    cap = runner.capture(out)
    prog = _live_program()

    def poisoned(*arrays):
        raise RuntimeError("data-dependent control flow reached a tracer")

    # poison BOTH compiled forms: xla fails → numpy fails → interp
    monkeypatch.setattr(prog, "_jit", poisoned, raising=False)
    monkeypatch.setattr(prog, "_np_fn", poisoned)
    monkeypatch.setattr(prog, "_np_sub_fn", None, raising=False)
    with caplog.at_level(logging.WARNING, logger="pathway_tpu.autojit"):
        runner.run_batch(n_workers=1)
    stats = autojit.autojit_stats()
    G.clear()

    assert list(cap.events) == off
    assert prog.backend == "interp"
    assert stats["demotions"] >= 1
    demote_logs = [r for r in caplog.records if "demoted" in r.message]
    # loudly-ONCE per backend hop, not once per batch (4 ticks ran)
    assert 1 <= len(demote_logs) <= 2


def test_verify_mismatch_demotes_and_keeps_interpreter_result(monkeypatch):
    """Verify-then-trust: a first-batch cell mismatch (simulated wrong
    compiled output) demotes and the interpreter's values win."""
    rows = [(int(i), i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sb=boost(t.x))

    off, _ = _run_events(build, "0", monkeypatch)

    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    G.clear()
    autojit.reset_stats()
    out = build()
    runner = GraphRunner()
    cap = runner.capture(out)
    prog = _live_program()

    def wrong(*arrays):
        return (np.zeros_like(arrays[0]),)  # plausible dtype, wrong values

    monkeypatch.setattr(prog, "_jit", wrong, raising=False)
    monkeypatch.setattr(prog, "_np_fn", wrong)
    monkeypatch.setattr(prog, "_np_sub_fn", None, raising=False)
    runner.run_batch(n_workers=1)
    stats = autojit.autojit_stats()
    G.clear()
    assert list(cap.events) == off
    assert prog.backend == "interp"
    assert stats["demotions"] >= 1


def test_untraceable_body_never_fuses(monkeypatch):
    """A UDF body the classifier cannot admit (truthiness over operands —
    Python returns an OPERAND, arrays cannot) stays interpreted: no
    program, no demotion noise, identical output."""

    @pw.udf
    def sneaky(x: int) -> int:
        return x or 7  # BoolOp: returns an operand by truthiness

    rows = [(int(i % 3), i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(s=sneaky(t.x))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 0
    assert on_stats["demotions"] == 0


# ---------------------------------------------------------------------------
# host/device map split (WindVE-style overlap)
# ---------------------------------------------------------------------------

def test_map_split_lowering_and_identity(monkeypatch):
    """A select carrying both a fusable chain and a host-only UDF lowers
    into map_host + map_dev + ZipAligned, the device side marked
    device_bound, and the output matches the unsplit interpreted run."""
    rows = [(int(i), float(i) / 9.0, i // 16, 1) for i in range(64)]
    schema = sch.schema_from_types(x=int, y=float)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sb=boost(t.x), sg=gate(t.y), tg=tag(t.x))

    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    G.clear()
    autojit.reset_stats()
    out = build()
    runner = GraphRunner()
    cap = runner.capture(out)
    names = {n.name: type(n.op).__name__ for n in runner.graph.nodes}
    assert any(k.startswith("map_host:") for k in names)
    assert any(k.startswith("map_dev:") for k in names)
    assert "ZipAlignedOperator" in names.values()
    dev = next(n for n in runner.graph.nodes
               if n.name.startswith("map_dev:"))
    assert getattr(dev.op, "device_bound", False)
    runner.run_batch(n_workers=1)
    on = list(cap.events)
    G.clear()

    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on


def test_no_split_without_host_udf(monkeypatch):
    """All-fusable selects keep ONE operator — the split only pays when
    there is host-only work to overlap."""
    rows = [(int(i), i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(x=int)
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    G.clear()
    t = table_from_rows(schema, rows, is_stream=True)
    out = t.select(sb=boost(t.x), st=stepi(t.x))
    runner = GraphRunner()
    runner.capture(out)
    names = [n.name for n in runner.graph.nodes]
    assert not any(k.startswith(("map_host:", "map_dev:")) for k in names)


def test_zip_aligned_misalignment_raises():
    from pathway_tpu.engine.delta import Delta
    from pathway_tpu.engine.operators import ZipAlignedOperator

    op = ZipAlignedOperator(((0, 0), (1, 0)))
    left = Delta([(1, ("a",), 1)])
    right = Delta([(2, ("b",), 1)])
    with pytest.raises(RuntimeError, match="lost alignment"):
        op.step(0, [left, right])
    ok = op.step(0, [Delta([(1, ("a",), 1)]), Delta([(1, ("b",), 1)])])
    assert ok.entries == [(1, ("a", "b"), 1)]


# ---------------------------------------------------------------------------
# warmup walks the bucket ladder
# ---------------------------------------------------------------------------

def test_warmup_walks_buckets_then_serving_compiles_nothing(monkeypatch):
    """pw.warmup after building the runner compiles every power-of-two
    bucket (8..max); the subsequent run adds NO compiles — first-tick
    compile latency moved out of serving."""
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    monkeypatch.setenv("PATHWAY_AUTO_JIT_WARM_MAX", "256")
    G.clear()
    autojit.reset_stats()
    rows = [(int(i), i // 100, 1) for i in range(200)]
    schema = sch.schema_from_types(x=int)
    t = table_from_rows(schema, rows, is_stream=True)
    out = t.select(sb=boost(t.x))
    runner = GraphRunner()
    cap = runner.capture(out)
    prog = _live_program()
    if prog.backend != "xla":  # CI without a usable jax backend
        pytest.skip("XLA backend unavailable for the fused program")
    warm = pw.warmup(cache=False)
    entries = [e for e in warm["compiled"] if e[0] == "autojit"]
    # ladder 8,16,32,64,128,256 → 6 buckets, each counted as a compile
    assert len(entries) == 6
    assert autojit.autojit_stats()["compiles"] == 6
    runner.run_batch(n_workers=1)  # 100-row ticks → bucket 128 (walked)
    assert autojit.autojit_stats()["compiles"] == 6
    assert autojit.autojit_stats()["device_dispatches"] >= 1
    assert [r for _, r, _, d in cap.events if d > 0]
    G.clear()


def test_warmup_autojit_disabled_is_noop(monkeypatch):
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "0")
    assert autojit.warm_registered() == []


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_closure_over_module_fuses(monkeypatch):
    """A UDF defined inside a function whose enclosing scope imported
    math still fuses (module-valued closure cells are process singletons
    — the regression that kept bench UDFs interpreted)."""
    def make_udf():
        import math  # noqa: F401 — deliberately shadows the module global

        @pw.udf
        def local_root(y: float) -> float:
            return math.sqrt(y) + 0.5

        return local_root

    local_root = make_udf()
    rows = [(float(i) / 7.0, i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(y=float)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sn=local_root(t.y))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 1
    assert on_stats["vector_dispatches"] >= 1  # math body → numpy partition


def test_locally_imported_decorator_still_fuses(monkeypatch):
    """A UDF decorated via a name imported in the ENCLOSING function
    (`import pathway_tpu as pw2` inside a factory — the bench's shape)
    must fuse: decorators resolve at def time, not per call, so the
    global-read gate must only inspect the body."""
    def make_udf():
        import pathway_tpu as pw2

        @pw2.udf
        def triple(x: int) -> int:
            return x * 3 + 1

        return triple

    triple = make_udf()
    rows = [(int(i), i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(s=triple(t.x))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 1
    assert (on_stats["device_dispatches"] + on_stats["vector_dispatches"]) > 0


def test_non_module_closure_never_fuses(monkeypatch):
    """A UDF closing over a mutable value must NOT be frozen — the cell
    could change under the fused program's feet. It stays interpreted."""
    factor = [3]

    def make_udf():
        k = factor[0]

        @pw.udf
        def scaled(x: int) -> int:
            return x * k

        return scaled

    scaled = make_udf()
    rows = [(int(i), i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(s=scaled(t.x))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off


def test_int_overflow_proof_bars_unprovable_trees(monkeypatch):
    """cube(x) needs 93 bits on guarded leaves — provably past int64, so
    the tree never fuses and Python bigint semantics hold exactly."""
    big = 2_000_000_000  # < 2^31: passes the cell guard
    rows = [(big, 0, 1)] * 16
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(c=cube(t.x))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 0  # nothing eligible fused
    got = [r[0] for _, r, _, d in on if d > 0]
    assert got == [big ** 3] * 16  # exact bigint, no int64 wrap


def test_mod_bound_uses_right_operand(monkeypatch):
    """|a % b| < |b|: the proof must bound modulo by the RIGHT operand.
    (-1 % y) is y-1, so (-1 % y) * x * x reaches ~2^93 from guarded
    leaves — a left-operand bound would 'prove' it safe at 63 bits and
    int64 would wrap silently on big inputs while the interpreter
    returns exact bigints."""

    @pw.udf
    def modmul(x: int, y: int) -> int:
        return (-1 % y) * x * x

    big = 2_000_000_000
    rows = [(big, big, 0, 1)] * 16
    schema = sch.schema_from_types(x=int, y=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(m=modmul(t.x, t.y))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 0  # provably past int64: never fuses
    got = [r[0] for _, r, _, d in on if d > 0]
    assert got == [(-1 % big) * big * big] * 16  # exact bigint


def test_unary_minus_preserves_negative_zero(monkeypatch):
    """-x must be true negation, not 0 - x: the latter turns -0.0 into
    +0.0, a byte divergence == cannot see."""
    rows = [(0.0 if i % 2 else float(i) / 8.0, i // 16, 1)
            for i in range(32)]
    schema = sch.schema_from_types(y=float)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(n=-gate(t.y))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert (on_stats["device_dispatches"]
            + on_stats["vector_dispatches"]) > 0  # non-vacuous
    zeros = [r[0] for _, r, _, d in on if d > 0 and r[0] == 0.0]
    assert zeros and all(math.copysign(1.0, z) == -1.0 for z in zeros)


def test_split_bail_discards_phantom_programs(monkeypatch):
    """A probed-then-bailed host/device split (host side non-
    deterministic → the aligned zip cannot be used) must not leave its
    FusedPrograms in the stats: /metrics counts only programs that can
    dispatch."""

    @pw.udf  # NOT deterministic → host_nd → split bails
    def tag_nd(x: int) -> str:
        return f"t-{x % 5}"

    rows = [(int(i), i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sb=boost(t.x), tg=tag_nd(t.x))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    # ONE live program (the full map's) — the split's probe compile was
    # backed out when it bailed
    assert on_stats["programs"] == 1


_SCALE = 7.5  # non-module global: fused snapshots would go stale


def test_mixed_int_float_comparison_past_2_53_not_fused(monkeypatch):
    """Python compares int-vs-float exactly; numpy/XLA promote int64 to
    float64 and round past 2^53. A comparison whose int side can exceed
    53 bits must stay interpreted."""
    @pw.udf
    def past53lit(x: int) -> bool:
        return x * x > 4611686014132420608.0  # x*x provable to 62 bits

    big = 2147483647  # x*x = 2^62-ish, one past float64's exact range
    rows = [(big, 0, 1)] * 16
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(c=past53lit(t.x))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 0
    got = [r[0] for _, r, _, d in on if d > 0]
    assert got == [big * big > 4611686014132420608.0] * 16  # exact


def test_bitwise_ops_never_fuse(monkeypatch):
    """Two's complement defeats magnitude bounds on negatives:
    -1 & v == v, so (-1 & (x*x)) * x reaches ~2^93 from guarded leaves.
    Bitwise bodies stay interpreted."""

    @pw.udf
    def bitmul(x: int) -> int:
        return (-1 & (x * x)) * x

    big = 2147483647
    rows = [(big, 0, 1)] * 16
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(m=bitmul(t.x))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 0
    got = [r[0] for _, r, _, d in on if d > 0]
    assert got == [(-1 & (big * big)) * big] * 16  # exact bigint


def test_int_cast_products_not_fused_without_declared_int(monkeypatch):
    """int() casts mint int64 values up to 2^62 even in a body whose
    PREDICTED return kind is float — their products wrap. The cast must
    force the overflow proof regardless of the predicted kind."""
    rows = [(0.5 + i / 64.0, i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(y=float)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(v=pw.apply(
            lambda y: int(y * 1e17) * int(y * 1e17), t.y))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 0
    got = [r[0] for _, r, _, d in on if d > 0]
    assert got and all(isinstance(v, int) and v > (1 << 63) for v in got)


def test_non_module_global_read_not_fused(monkeypatch):
    """A body reading a module-level non-module name (a tunable) must
    stay interpreted: the fused program would freeze the value while the
    interpreter reads it live, and the nondet replay cache would be
    dropped for a body that is NOT verified deterministic."""

    @pw.udf
    def scaled(y: float) -> float:
        return y * _SCALE

    rows = [(float(i) / 9.0, i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(y=float)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(s=scaled(t.y))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    assert on_stats["programs"] == 0
    # and the lowering kept the caching operator for the unverified body
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    G.clear()
    t = table_from_rows(schema, list(rows), is_stream=True)
    out = t.select(s=scaled(t.y))
    runner = GraphRunner()
    runner.capture(out)
    ops = {type(n.op).__name__ for n in runner.graph.nodes}
    assert "DeterministicMapOperator" in ops
    G.clear()


def test_int64_min_cell_guarded(monkeypatch):
    """-2**63 is the adversarial guard cell: np.abs of it WRAPS (stays
    negative), so a magnitude check via abs would admit it to the fused
    path where the overflow proof assumed |v| < 2^31. It must be routed
    to the interpreter and stay byte-identical."""
    evil = -(1 << 63)
    rows = [(evil if i % 4 == 0 else i, i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sb=boost(t.x))

    on, on_stats = _run_events(build, "1", monkeypatch)
    off, _ = _run_events(build, "0", monkeypatch)
    assert on == off
    got = {r[0] for _, r, _, d in on if d > 0}
    assert evil * 3 + 7 in got  # exact bigint arithmetic preserved


def test_stats_and_status_surfaces(monkeypatch):
    rows = [(int(i), i // 16, 1) for i in range(32)]
    schema = sch.schema_from_types(x=int)

    def build():
        t = table_from_rows(schema, list(rows), is_stream=True)
        return t.select(sb=boost(t.x))

    _, stats = _run_events(build, "1", monkeypatch)
    assert stats["enabled"] is True
    assert set(stats) >= {"programs", "compiles", "demotions",
                          "device_dispatches", "vector_dispatches",
                          "fallback_batches", "live_programs",
                          "bucket_count"}
    monkeypatch.setenv("PATHWAY_AUTO_JIT", "0")
    assert autojit.autojit_stats()["enabled"] is False
