"""Wire formats: DSV parser/formatter, Debezium CDC (Postgres + MongoDB),
psql updates/snapshot formatters, plus fs/debezium connector integration
(reference: src/connectors/data_format.rs:377,816,931,1504,1563; cases
mirror tests/integration/test_debezium.rs)."""

from __future__ import annotations

import json

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.formats import (DEBEZIUM_STANDARD_SEPARATOR,
                                    DebeziumMessageParser, DsvFormatter,
                                    DsvParser, ParseError,
                                    PsqlSnapshotFormatter,
                                    PsqlUpdatesFormatter)
from tests.utils import rows_of


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


# ---------------------------------------------------------------------------
# DSV
# ---------------------------------------------------------------------------

class _S(pw.Schema):
    name: str
    age: int
    score: float
    active: bool


def test_dsv_parser_typed():
    p = DsvParser(separator="|", schema=_S)
    events = p.parse_lines(
        "name|age|score|active\nalice|31|1.5|true\nbob|28|2.25|F\n")
    assert [e.values for e in events] == [
        {"name": "alice", "age": 31, "score": 1.5, "active": True},
        {"name": "bob", "age": 28, "score": 2.25, "active": False},
    ]


def test_dsv_parser_quoting_and_key():
    p = DsvParser(separator=",", key_columns=["id"])
    p.parse_header("id,text")
    ev = p.parse_line('7,"hello, world"')
    assert ev.values == {"id": "7", "text": "hello, world"}
    assert ev.key == ("7",)


def test_dsv_parse_errors():
    p = DsvParser(separator=";")
    p.parse_header("a;b")
    with pytest.raises(ParseError, match="3 fields, header has 2"):
        p.parse_line("1;2;3")
    with pytest.raises(ParseError, match="single character"):
        DsvParser(separator="||")
    typed = DsvParser(separator=",", schema=_S)
    typed.parse_header("name,age,score,active")
    with pytest.raises(ValueError):
        typed.parse_line("x,notanint,1.0,true")
    with pytest.raises(ParseError, match="as bool"):
        typed.parse_line("x,1,1.0,maybe")


def test_dsv_formatter_roundtrip():
    f = DsvFormatter(["name", "age"], separator="|")
    assert f.header() == "name|age|time|diff"
    line = f.format({"name": "a|b", "age": 3}, 10, -1)
    p = DsvParser(separator="|")
    p.parse_header(f.header())
    ev = p.parse_line(line)
    assert ev.values == {"name": "a|b", "age": "3", "time": "10",
                         "diff": "-1"}


def test_fs_read_dsv(tmp_path):
    (tmp_path / "d.dsv").write_text(
        "name|age|score|active\nalice|31|1.5|true\nbob|28|2.25|no\n")
    t = pw.io.fs.read(str(tmp_path / "d.dsv"), format="dsv", schema=_S,
                      mode="static", dsv_separator="|")
    got = sorted(rows_of(t))
    assert got == [("alice", 31, 1.5, True), ("bob", 28, 2.25, False)]


# ---------------------------------------------------------------------------
# Debezium
# ---------------------------------------------------------------------------

def _msg(op, before=None, after=None, key=None):
    value = json.dumps({"payload": {"op": op, "before": before,
                                    "after": after}})
    kv = json.dumps({"payload": key if key is not None else {}})
    return kv, value


def test_debezium_postgres_ops():
    p = DebeziumMessageParser(["id", "name"], db_type="postgres")
    evs = p.parse_kv(*_msg("c", after={"id": 1, "name": "a"}))
    assert [(e.kind, e.values) for e in evs] == [
        ("insert", {"id": 1, "name": "a"})]
    evs = p.parse_kv(*_msg("r", after={"id": 2, "name": "b"}))
    assert evs[0].kind == "insert"
    evs = p.parse_kv(*_msg("u", before={"id": 1, "name": "a"},
                           after={"id": 1, "name": "z"}))
    assert [(e.kind, e.values["name"]) for e in evs] == [
        ("delete", "a"), ("insert", "z")]
    evs = p.parse_kv(*_msg("d", before={"id": 1, "name": "z"}))
    assert [(e.kind, e.values) for e in evs] == [
        ("delete", {"id": 1, "name": "z"})]


def test_debezium_mongodb_upserts():
    p = DebeziumMessageParser(["id", "name"], ["id"], db_type="mongodb")
    # MongoDB serializes the after-image as a JSON *string*
    value = json.dumps({"payload": {
        "op": "u", "after": json.dumps({"id": 5, "name": "n"})}})
    key = json.dumps({"payload": {"id": 5}})
    evs = p.parse_kv(key, value)
    assert [(e.kind, e.key, e.values) for e in evs] == [
        ("upsert", (5,), {"id": 5, "name": "n"})]
    evs = p.parse_kv(key, json.dumps({"payload": {"op": "d"}}))
    assert [(e.kind, e.key, e.values) for e in evs] == [
        ("upsert", (5,), None)]


def test_debezium_tombstone_and_errors():
    p = DebeziumMessageParser(["id"], db_type="postgres")
    assert p.parse_kv("{}", "null") == []  # kafka compaction tombstone
    with pytest.raises(ParseError, match="payload"):
        p.parse_kv("{}", json.dumps({"nope": 1}))
    with pytest.raises(ParseError, match="operation"):
        p.parse_kv("{}", json.dumps({"payload": {}}))
    with pytest.raises(ParseError, match="unsupported"):
        p.parse_kv("{}", json.dumps({"payload": {"op": "x"}}))
    with pytest.raises(ParseError, match="JSON"):
        p.parse_kv("{}", "{broken")
    with pytest.raises(ParseError, match="key/value"):
        p.parse_line("only-one-token")


def test_debezium_file_replay_end_to_end(tmp_path):
    """CDC log file → live table with exact retraction semantics."""
    sep = DEBEZIUM_STANDARD_SEPARATOR
    lines = []
    for op, before, after in [
        ("c", None, {"id": 1, "name": "a"}),
        ("c", None, {"id": 2, "name": "b"}),
        ("u", {"id": 1, "name": "a"}, {"id": 1, "name": "z"}),
        ("d", {"id": 2, "name": "b"}, None),
    ]:
        k, v = _msg(op, before=before, after=after)
        lines.append(k + sep + v)
    (tmp_path / "cdc.log").write_text("\n".join(lines) + "\n")

    class CDC(pw.Schema):
        id: int = pw.column_definition(primary_key=True)
        name: str

    t = pw.io.debezium.read_from_file(
        str(tmp_path / "cdc.log"), schema=CDC, mode="static")
    got = sorted(rows_of(t))
    assert got == [(1, "z")]


# ---------------------------------------------------------------------------
# psql formatters
# ---------------------------------------------------------------------------

def test_psql_updates_formatter():
    f = PsqlUpdatesFormatter("tbl", ["id", "name"])
    sql, params = f.format({"id": 1, "name": "a"}, 42, 1)
    assert sql == ("INSERT INTO tbl (id,name,time,diff) "
                   "VALUES ($1,$2,42,1)")
    assert params == [1, "a"]


def test_psql_snapshot_formatter():
    f = PsqlSnapshotFormatter("tbl", ["id"], ["id", "name"])
    sql, params = f.format({"id": 1, "name": "a"}, 7, -1)
    assert "ON CONFLICT (id) DO UPDATE SET name=$2,time=7,diff=-1" in sql
    assert "WHERE tbl.time<7 OR (tbl.time=7 AND tbl.diff=-1)" in sql
    assert params == [1, "a"]
    with pytest.raises(ParseError, match="must be a value column"):
        PsqlSnapshotFormatter("t", ["missing"], ["id"])
