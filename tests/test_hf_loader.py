"""hf_loader: HF BERT checkpoint → JAX encoder parity.

The golden test constructs a small random BertModel OFFLINE with the
in-image transformers/torch, saves it as a real checkpoint directory, loads
it through pathway_tpu.models.hf_loader, and compares the JAX forward pass
against torch's — validating the full weight mapping (transposes, layernorm
placement, erf-gelu, CLS pooling) without any network. A second test runs
against a real BGE checkpoint only when one is in the local HF cache."""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from pathway_tpu.models.encoder import encode  # noqa: E402
from pathway_tpu.models.hf_loader import (find_local_checkpoint,  # noqa: E402
                                          load_checkpoint, load_model)

VOCAB_WORDS = ["the", "quick", "brown", "fox", "jump", "##ed", "##s",
               "over", "lazy", "dog", "un", "##believ", "##able"]


def _make_checkpoint(tmp_path, save_format):
    cfg = transformers.BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=48,
        max_position_embeddings=64, type_vocab_size=2)
    torch.manual_seed(0)
    model = transformers.BertModel(cfg)
    model.eval()
    d = tmp_path / "ckpt"
    model.save_pretrained(str(d), safe_serialization=(save_format == "st"))
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + VOCAB_WORDS
    vocab += [f"tok{i}" for i in range(64 - len(vocab))]
    (d / "vocab.txt").write_text("\n".join(vocab) + "\n")
    return model, d


@pytest.mark.parametrize("save_format", ["st", "bin"])
def test_random_bert_checkpoint_forward_parity(tmp_path, save_format):
    model, d = _make_checkpoint(tmp_path, save_format)
    params, config, tokenizer = load_checkpoint(
        str(d), compute_dtype=jnp.float32)
    assert config.hidden == 32 and config.layers == 2
    assert tokenizer is not None and tokenizer.vocab_size == 64

    ids, mask = tokenizer.batch(
        ["the quick brown fox", "unbelievable jumps over the lazy dog"],
        pad_to=16)
    with torch.no_grad():
        out = model(input_ids=torch.tensor(ids, dtype=torch.long),
                    attention_mask=torch.tensor(mask, dtype=torch.long))
    want_hidden = out.last_hidden_state.numpy()
    want = want_hidden[:, 0]
    want = want / np.linalg.norm(want, axis=1, keepdims=True)

    got = np.asarray(encode(params, jnp.asarray(ids), jnp.asarray(mask),
                            config=config))
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_bge_small_golden_if_cached():
    """Real-checkpoint golden: only runs when BGE-small is in the local HF
    cache (zero-egress builds skip)."""
    if find_local_checkpoint("BAAI/bge-small-en-v1.5") is None:
        pytest.skip("BAAI/bge-small-en-v1.5 not in local HF cache")
    params, config, tokenizer = load_model(
        "BAAI/bge-small-en-v1.5", compute_dtype=jnp.float32)
    assert config.hidden == 384 and config.layers == 12
    ids, mask = tokenizer.batch(["a photo of a cat"], pad_to=16)
    got = np.asarray(encode(params, jnp.asarray(ids), jnp.asarray(mask),
                            config=config))
    st = transformers.AutoModel.from_pretrained(
        find_local_checkpoint("BAAI/bge-small-en-v1.5"))
    st.eval()
    with torch.no_grad():
        out = st(input_ids=torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long))
    want = out.last_hidden_state.numpy()[:, 0]
    want = want / np.linalg.norm(want, axis=1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_missing_checkpoint_message():
    with pytest.raises(FileNotFoundError, match="no local checkpoint"):
        load_model("nonexistent/model-xyz")
