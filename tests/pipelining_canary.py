"""Pipelining smoke gate: the async device path must actually run.

Drives ``examples/streaming_etl.py``'s real graph (``build()``) over a
small order feed with ``PATHWAY_DEVICE_INFLIGHT=2`` and asserts, from the
live ``/metrics`` endpoint and the scheduler's bridge counters, that

1. the device bridge resolved > 0 legs (the traceable ``demand_score``
   UDF and its downstream window/sink rode the async leg — a silent fall
   back to synchronous execution fails the gate), and
2. the CSV output is complete and identical to a ``PATHWAY_DEVICE_INFLIGHT=1``
   (synchronous) run — overlap must never change results.

Exits 0 iff both hold. Run: ``python tests/pipelining_canary.py``
(same pattern as watchdog_canary.py: the gate is only trusted because a
seeded property is proven end to end).
"""

from __future__ import annotations

import csv
import json
import os
import pathlib
import sys
import tempfile
import urllib.request


def _write_feed(root: pathlib.Path) -> tuple[str, str]:
    orders = root / "orders"
    orders.mkdir()
    rows = [{"item": f"i{i % 4}", "qty": 1 + i % 3,
             "price": 2.5 * (1 + i % 5), "ts": 60 * i} for i in range(24)]
    (orders / "orders.jsonl").write_text(
        "\n".join(json.dumps(r) for r in rows) + "\n")
    cats = root / "categories.csv"
    cats.write_text("item,category\n" + "\n".join(
        f"i{i},cat{i % 2}" for i in range(4)) + "\n")
    return str(orders), str(cats)


def _run(inflight: int, with_http: bool) -> tuple[list, dict | None, str]:
    os.environ["PATHWAY_DEVICE_INFLIGHT"] = str(inflight)
    import pathway_tpu as pw
    from examples.streaming_etl import build
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        orders_dir, cats_csv = _write_feed(root)
        out_csv = str(root / "out.csv")
        build(orders_dir, cats_csv, out_csv)
        # the order feed tails its directory (mode="streaming", never
        # closes): run on a background thread, observe the live runtime,
        # then stop it once the bridge and the sink have visibly worked
        import threading

        metrics_txt = ""

        def _run_pipeline():
            pw.run(with_http_server=with_http)

        t = threading.Thread(target=_run_pipeline, daemon=True)
        t.start()
        import time

        deadline = time.monotonic() + 30.0
        from pathway_tpu.engine import streaming as _streaming

        rt = None
        while time.monotonic() < deadline and rt is None:
            live = list(_streaming._ACTIVE_RUNTIMES)
            rt = live[0] if live else None
            time.sleep(0.05)
        assert rt is not None, "runtime did not start"
        # wait until the windowed rows visibly flowed AND the sink went
        # quiescent (same size across two polls — the finite feed is fully
        # ingested in one directory scan, so quiescence means complete)
        last_size = -1
        while time.monotonic() < deadline:
            stats = rt.scheduler.bridge_stats()
            legs_ok = stats is None or stats["legs_resolved"] > 0
            size = os.path.getsize(out_csv) if os.path.exists(out_csv) \
                else 0
            if legs_ok and size > 0 and size == last_size:
                break
            last_size = size
            time.sleep(0.25)
        if with_http and rt.http_server is not None:
            url = f"http://127.0.0.1:{rt.http_server.port}/metrics"
            metrics_txt = urllib.request.urlopen(url, timeout=5).read() \
                .decode()
        rt.scheduler.resolve_barrier()
        stats = rt.scheduler.bridge_stats()
        _streaming.stop_all()
        t.join(15.0)
        rows = _consolidate_csv(out_csv)
        G.clear()
        return rows, stats, metrics_txt


def _consolidate_csv(path: str) -> list:
    """Final state from a CSV event log (trailing time/diff columns):
    tick boundaries differ run to run, so the comparable artifact is the
    net row multiset, not the raw event sequence."""
    if not os.path.exists(path):
        return []
    acc: dict[tuple, int] = {}
    with open(path) as f:
        reader = csv.reader(f)
        header = next(reader, None)
        if header is None:
            return []
        t_pos, d_pos = header.index("time"), header.index("diff")
        for r in reader:
            key = tuple(v for i, v in enumerate(r) if i not in (t_pos, d_pos))
            acc[key] = acc.get(key, 0) + int(r[d_pos])
    return sorted(k for k, n in acc.items() for _ in range(n) if n > 0)


def main() -> int:
    pipelined_rows, stats, metrics_txt = _run(2, with_http=True)
    if not stats or stats["legs_resolved"] <= 0:
        print(f"FAIL: device bridge never resolved a leg: {stats}",
              file=sys.stderr)
        return 1
    if "pathway_tpu_device_legs_resolved" not in metrics_txt:
        print("FAIL: /metrics does not export device-bridge counters",
              file=sys.stderr)
        return 1
    for line in metrics_txt.splitlines():
        if line.startswith("pathway_tpu_device_legs_resolved"):
            if float(line.split()[-1]) <= 0:
                print(f"FAIL: /metrics reports zero resolved legs: {line}",
                      file=sys.stderr)
                return 1
    sync_rows, sync_stats, _ = _run(1, with_http=False)
    if sync_stats is not None:
        print(f"FAIL: PATHWAY_DEVICE_INFLIGHT=1 still built a bridge: "
              f"{sync_stats}", file=sys.stderr)
        return 1
    if not pipelined_rows or pipelined_rows != sync_rows:
        print(f"FAIL: pipelined CSV != synchronous CSV "
              f"({len(pipelined_rows)} vs {len(sync_rows)} rows)",
              file=sys.stderr)
        return 1
    print(f"OK: bridge resolved {stats['legs_resolved']} legs "
          f"(overlap {stats['overlap_ratio']:.0%}), outputs identical to "
          f"synchronous run ({len(pipelined_rows)} CSV rows)")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
