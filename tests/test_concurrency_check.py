"""Concurrency analyzer (static_check/concurrency_check.py): one
true-positive and one true-negative per PWT201–PWT208 code, the waiver
mechanism, the thread/lock inventories, the engine-dogfood gate, and the
CLI ``--concurrency`` front door (mirrors tests/test_shard_check.py)."""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

from pathway_tpu.internals.static_check import (check_concurrency,
                                                concurrency_inventory)


def run_check(tmp_path, source: str):
    f = tmp_path / "mod_under_test.py"
    f.write_text(textwrap.dedent(source))
    return check_concurrency([str(f)])


def codes(diags):
    return sorted(d.code for d in diags)


def only(diags, code):
    return [d for d in diags if d.code == code]


# ---------------------------------------------------------------------------
# PWT201 — lock-order inversion
# ---------------------------------------------------------------------------

_INVERSION = """
    import threading

    class Engine:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def ingest(self):
            with self._a:
                with self._b:
                    pass

        def query(self):
            with self._b:
                with self._a:
                    pass
"""


def test_pwt201_inversion_is_error(tmp_path):
    diags = only(run_check(tmp_path, _INVERSION), "PWT201")
    assert len(diags) == 1  # one report per inverted pair, not per edge
    assert diags[0].is_error
    assert "deadlock" in diags[0].message


def test_pwt201_negative_consistent_order(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ingest(self):
                with self._a:
                    with self._b:
                        pass

            def query(self):
                with self._a:
                    with self._b:
                        pass
    """)
    assert only(diags, "PWT201") == []


def test_pwt201_inversion_through_method_call(tmp_path):
    # `with a: self.helper()` where helper takes b, vs `with b: ... a` —
    # one self-call level of propagation must close the cycle
    diags = run_check(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def helper(self):
                with self._b:
                    pass

            def ingest(self):
                with self._a:
                    self.helper()

            def query(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert len(only(diags, "PWT201")) == 1


# ---------------------------------------------------------------------------
# PWT202 — unguarded cross-thread writes
# ---------------------------------------------------------------------------

_RACY = """
    import threading

    class Worker:
        def __init__(self):
            self.counter = 0
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

        def bump(self):
            self.counter += 1

        def _run(self):
            while True:
                self.counter += 1
"""


def test_pwt202_unguarded_cross_root_write(tmp_path):
    diags = only(run_check(tmp_path, _RACY), "PWT202")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "Worker.counter" in diags[0].message


def test_pwt202_negative_common_guard(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class Worker:
            def __init__(self):
                self.counter = 0
                self._lock = threading.Lock()
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def bump(self):
                with self._lock:
                    self.counter += 1

            def _run(self):
                while True:
                    with self._lock:
                        self.counter += 1
    """)
    assert only(diags, "PWT202") == []


def test_pwt202_negative_guard_via_calling_method(tmp_path):
    # the write sits in a helper that every root calls under the lock —
    # guaranteed-held propagation must count it as guarded
    diags = run_check(tmp_path, """
        import threading

        class Worker:
            def __init__(self):
                self.counter = 0
                self._lock = threading.Lock()
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def _bump_locked(self):
                self.counter += 1

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _run(self):
                while True:
                    with self._lock:
                        self._bump_locked()
    """)
    assert only(diags, "PWT202") == []


def test_pwt202_negative_init_writes_do_not_count(tmp_path):
    # __init__ runs before any thread exists
    diags = run_check(tmp_path, """
        import threading

        class Worker:
            def __init__(self):
                self.counter = 0
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)

            def _run(self):
                while True:
                    self.counter += 1
    """)
    assert only(diags, "PWT202") == []


# ---------------------------------------------------------------------------
# PWT203 — lock held across blocking call
# ---------------------------------------------------------------------------

_HELD_FSYNC = """
    import os
    import threading

    class Log:
        def __init__(self):
            self._lock = threading.Lock()
            self._f = None

        def append(self, blob):
            with self._lock:
                os.fsync(self._f.fileno())
"""


def test_pwt203_fsync_under_lock(tmp_path):
    diags = only(run_check(tmp_path, _HELD_FSYNC), "PWT203")
    assert len(diags) == 1
    assert "os.fsync" in diags[0].message


def test_pwt203_negative_fsync_outside_lock(tmp_path):
    diags = run_check(tmp_path, """
        import os
        import threading

        class Log:
            def __init__(self):
                self._lock = threading.Lock()
                self._f = None

            def append(self, blob):
                with self._lock:
                    pending = blob
                os.fsync(self._f.fileno())
    """)
    assert only(diags, "PWT203") == []


def test_pwt203_bridge_submit_under_lock(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class Loop:
            def __init__(self, bridge):
                self._state_lock = threading.Lock()
                self._bridge = bridge

            def tick(self, t, leg):
                with self._state_lock:
                    self._bridge.submit(t, leg)
    """)
    assert len(only(diags, "PWT203")) == 1


def test_pwt203_negative_pool_submit_is_not_blocking(tmp_path):
    # ThreadPoolExecutor.submit returns immediately — only bridge-shaped
    # receivers count
    diags = run_check(tmp_path, """
        import threading

        class Loop:
            def __init__(self, pool):
                self._state_lock = threading.Lock()
                self._pool = pool

            def tick(self, fn):
                with self._state_lock:
                    self._pool.submit(fn)
    """)
    assert only(diags, "PWT203") == []


def test_pwt203_wait_with_second_lock_held(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class TwoLocks:
            def __init__(self):
                self._state = threading.Lock()
                self._cv = threading.Condition()
                self.ready = False

            def consume(self):
                with self._state:
                    with self._cv:
                        while not self.ready:
                            self._cv.wait()
    """)
    assert len(only(diags, "PWT203")) == 1
    assert "releases" in only(diags, "PWT203")[0].message


# ---------------------------------------------------------------------------
# PWT204 — dropped daemon handle
# ---------------------------------------------------------------------------

def test_pwt204_dropped_daemon_handle(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        def fire_and_forget(fn):
            threading.Thread(target=fn, daemon=True).start()
    """)
    assert len(only(diags, "PWT204")) == 1


def test_pwt204_negative_kept_handles(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class Owner:
            def __init__(self):
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def _run(self):
                pass

        def start_joined(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            t.join()

        def start_returned(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            return t

        class Fleet:
            # the router idiom: the handle lands in the container
            # directly, never touching a local name
            def __init__(self):
                self._threads = []

            def start(self, fn):
                self._threads.append(threading.Thread(target=fn,
                                                      daemon=True))

        class Tracked:
            # the tracking-helper idiom: self.m(spawn(...)) where m
            # verifiably appends its parameter
            def __init__(self):
                self._threads = []

            def _track(self, t):
                self._threads = [x for x in self._threads
                                 if x.is_alive()]
                self._threads.append(t)

            def start(self, fn):
                self._track(threading.Thread(target=fn, daemon=True))
    """)
    assert only(diags, "PWT204") == []


def test_pwt204_helper_that_drops_is_still_flagged(tmp_path):
    # handing the handle to a same-class method is only keeping it if
    # that method actually stores it — a sink that ignores its argument
    # must not launder the drop
    diags = run_check(tmp_path, """
        import threading

        class Dropper:
            def _log(self, t):
                print(t.name)

            def start(self, fn):
                self._log(threading.Thread(target=fn, daemon=True))
    """)
    assert len(only(diags, "PWT204")) == 1


# ---------------------------------------------------------------------------
# PWT205 — Condition.wait without a predicate loop
# ---------------------------------------------------------------------------

def test_pwt205_wait_without_loop(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()

            def take(self):
                with self._cv:
                    self._cv.wait()
    """)
    hits = only(diags, "PWT205")
    assert len(hits) == 1
    assert hits[0].is_error


def test_pwt205_negative_loop_and_wait_for(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self.items = []

            def take(self):
                with self._cv:
                    while not self.items:
                        self._cv.wait()
                    return self.items.pop()

            def take2(self):
                with self._cv:
                    self._cv.wait_for(lambda: self.items)
                    return self.items.pop()
    """)
    assert only(diags, "PWT205") == []


# ---------------------------------------------------------------------------
# PWT206 — sleep-polling where an Event exists
# ---------------------------------------------------------------------------

def test_pwt206_sleep_poll_with_event(tmp_path):
    diags = run_check(tmp_path, """
        import threading
        import time

        class Loop:
            def __init__(self):
                self._stop = threading.Event()

            def run(self):
                while not self._stop.is_set():
                    time.sleep(0.05)
    """)
    assert len(only(diags, "PWT206")) == 1
    assert "_stop" in only(diags, "PWT206")[0].message


def test_pwt206_negative_event_wait_and_no_event(tmp_path):
    diags = run_check(tmp_path, """
        import threading
        import time

        class Loop:
            def __init__(self):
                self._stop = threading.Event()

            def run(self):
                while not self._stop.wait(0.05):
                    pass

        def module_level_retry():
            while True:
                time.sleep(0.05)
    """)
    # the Event.wait loop is the fix; the module-level retry loop has no
    # Event in scope to wait on
    assert only(diags, "PWT206") == []


# ---------------------------------------------------------------------------
# PWT207 — bare threading.Thread
# ---------------------------------------------------------------------------

def test_pwt207_raw_thread(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        def go(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            return t
    """)
    assert len(only(diags, "PWT207")) == 1


def test_pwt207_negative_factory_spawn(tmp_path):
    diags = run_check(tmp_path, """
        from pathway_tpu.engine.threads import spawn

        def go(fn):
            return spawn(fn, name="worker")
    """)
    assert only(diags, "PWT207") == []


def test_pwt207_raw_lock_construction(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        _LOCK = threading.Lock()
    """)
    hits = only(diags, "PWT207")
    assert len(hits) == 1
    assert "threading.Lock" in hits[0].message


def test_pwt207_negative_lock_factory_and_provider_module(tmp_path):
    # factory calls are fine, and a module DEFINING create_lock is the
    # provider — its own threading.Lock() constructions are exempt
    diags = run_check(tmp_path, """
        import threading

        def create_lock(name):
            return threading.Lock()
    """)
    assert only(diags, "PWT207") == []


def test_init_py_modules_get_package_qualified_ids(tmp_path):
    # two packages' __init__.py each define a module-global lock nested
    # in opposite orders relative to a shared class lock: distinct ids
    # (per package) mean no spurious cross-package inversion
    shared = """
        import threading

        _LOCK = threading.Lock()  # pwt-ok: PWT207

        class C_{pkg}:
            def __init__(self):
                self._mu = threading.Lock()

            def go(self):
                with {outer}:
                    with {inner}:
                        pass
    """
    for pkg, outer, inner in (("alpha", "_LOCK", "self._mu"),
                              ("beta", "self._mu", "_LOCK")):
        d = tmp_path / pkg
        d.mkdir()
        (d / "__init__.py").write_text(textwrap.dedent(
            shared.format(pkg=pkg, outer=outer, inner=inner)))
    diags = check_concurrency([str(tmp_path)])
    assert only(diags, "PWT201") == []
    inv = concurrency_inventory([str(tmp_path)])
    ids = {lk["lock_id"] for lk in inv["locks"]}
    assert "alpha._LOCK" in ids and "beta._LOCK" in ids


# ---------------------------------------------------------------------------
# PWT208 — notify outside the condition's with
# ---------------------------------------------------------------------------

def test_pwt208_notify_outside_with(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()

            def put(self, item):
                self._cv.notify_all()
    """)
    assert len(only(diags, "PWT208")) == 1
    assert only(diags, "PWT208")[0].is_error


def test_pwt208_negative_notify_inside_with(tmp_path):
    diags = run_check(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()

            def put(self, item):
                with self._cv:
                    self._cv.notify_all()
    """)
    assert only(diags, "PWT208") == []


# ---------------------------------------------------------------------------
# waivers
# ---------------------------------------------------------------------------

def test_waiver_suppresses_named_code(tmp_path):
    diags = run_check(tmp_path, """
        import os
        import threading

        class Log:
            def __init__(self):
                self._lock = threading.Lock()
                self._f = None

            def append(self, blob):
                with self._lock:
                    # pwt-ok: PWT203 — single-writer log, contention-free
                    os.fsync(self._f.fileno())
    """)
    assert only(diags, "PWT203") == []


def test_waiver_for_other_code_does_not_suppress(tmp_path):
    diags = run_check(tmp_path, """
        import os
        import threading

        class Log:
            def __init__(self):
                self._lock = threading.Lock()
                self._f = None

            def append(self, blob):
                with self._lock:
                    # pwt-ok: PWT204
                    os.fsync(self._f.fileno())
    """)
    assert len(only(diags, "PWT203")) == 1


def test_syntax_error_is_pwt000_not_silently_skipped(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def uh(:\n")
    diags = check_concurrency([str(f)])
    assert codes(diags) == ["PWT000"]
    assert diags[0].is_error


# ---------------------------------------------------------------------------
# inventories
# ---------------------------------------------------------------------------

def test_inventories(tmp_path):
    f = tmp_path / "inv.py"
    f.write_text(textwrap.dedent("""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()
                self._stop = threading.Event()
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def _run(self):
                with self._lock:
                    with self._cv:
                        pass
    """))
    inv = concurrency_inventory([str(f)])
    lock_ids = {lk["lock_id"]: lk["kind"] for lk in inv["locks"]}
    assert lock_ids["Engine._lock"] == "lock"
    assert lock_ids["Engine._cv"] == "condition"
    assert lock_ids["Engine._stop"] == "event"
    [t] = inv["threads"]
    assert t["target"] == "Engine._run"
    assert t["handle_kept"] is True
    assert ("Engine._lock", "Engine._cv") in [
        tuple(e) for e in inv["order_edges"]]


# ---------------------------------------------------------------------------
# dogfood: the engine itself must be clean (the CI gate's contract)
# ---------------------------------------------------------------------------

def test_engine_source_is_concurrency_clean():
    assert check_concurrency(["pathway_tpu/engine"]) == []


def test_io_and_parallel_sources_are_concurrency_clean():
    assert check_concurrency(["pathway_tpu/io", "pathway_tpu/parallel"]) \
        == []


def test_seeded_negative_example_trips_the_gate():
    diags = check_concurrency(["tests/concurrency_negative_example.py"])
    assert any(d.code == "PWT201" and d.is_error for d in diags)


# ---------------------------------------------------------------------------
# CLI front door
# ---------------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu", "check", *args],
        capture_output=True, text=True, env=None)


def test_cli_concurrency_clean_and_json():
    proc = _run_cli("--concurrency", "--json", "pathway_tpu/engine")
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["diagnostics"] == []
    targets = {t["target"] for t in payload["inventory"]["threads"]}
    assert "DeviceBridge._work" in targets
    assert "Watchdog._run" in targets


def test_cli_concurrency_seeded_inversion_fails():
    proc = _run_cli("--concurrency",
                    "tests/concurrency_negative_example.py")
    assert proc.returncode == 1
    assert "PWT201" in proc.stdout
