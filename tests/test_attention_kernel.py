"""Fused Pallas attention kernel (ops/attention.py) — CPU interpret-mode
parity with the encoder's XLA attention path (SURVEY §7: kernel-level unit
tests on the CPU jax backend)."""

import jax
import jax.numpy as jnp
import numpy as np

from pathway_tpu.models.encoder import _dense_attention
from pathway_tpu.ops.attention import flash_attention


def _rand(shape, seed):
    return jax.random.normal(jax.random.PRNGKey(seed), shape,
                             dtype=jnp.float32)


def test_kernel_matches_xla_attention():
    B, S, H, D = 2, 128, 6, 64
    q, k, v = _rand((B, S, H, D), 0), _rand((B, S, H, D), 1), _rand(
        (B, S, H, D), 2)
    mask = jnp.array(np.random.default_rng(0).random((B, S)) > 0.3)
    ref = _dense_attention(q, k, v, mask)
    got = flash_attention(q, k, v, mask, interpret=True)
    assert float(jnp.max(jnp.abs(ref - got))) < 1e-3


def test_kernel_all_valid_and_single_batch():
    B, S, H, D = 1, 64, 2, 32
    q, k, v = _rand((B, S, H, D), 3), _rand((B, S, H, D), 4), _rand(
        (B, S, H, D), 5)
    mask = jnp.ones((B, S), dtype=bool)
    ref = _dense_attention(q, k, v, mask)
    got = flash_attention(q, k, v, mask, interpret=True)
    assert float(jnp.max(jnp.abs(ref - got))) < 1e-3
