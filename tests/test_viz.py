"""stdlib.viz.plot — live Bokeh plotting (reference stdlib/viz/plotting.py).

Bokeh is not in this image, so the tests install a minimal stub that
mimics the ColumnDataSource.stream(rollover=...) contract and assert the
plot path drives it: immediately for static tables, after every closed
timestamp for streaming ones."""

from __future__ import annotations

import sys
import threading
import time
import types

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


class FakeColumnDataSource:
    def __init__(self, data=None):
        self.data = dict(data or {})
        self.streams = []  # (data, rollover)

    def stream(self, new_data, rollover=None):
        # real bokeh semantics: append, then trim to the LAST `rollover`
        # items (rollover=0 trims nothing — which is why the viz path must
        # clear by assignment, not by streaming an empty update)
        self.streams.append((dict(new_data), rollover))
        for k, v in new_data.items():
            self.data.setdefault(k, []).extend(v)
        if rollover:
            for k in self.data:
                self.data[k] = self.data[k][-rollover:]


class FakeFigure:
    document = None

    def scatter(self, *a, **kw):
        pass


@pytest.fixture()
def bokeh_stub(monkeypatch):
    bokeh = types.ModuleType("bokeh")
    models = types.ModuleType("bokeh.models")
    plotting = types.ModuleType("bokeh.plotting")
    models.ColumnDataSource = FakeColumnDataSource
    plotting.figure = lambda **kw: FakeFigure()
    bokeh.models = models
    bokeh.plotting = plotting
    monkeypatch.setitem(sys.modules, "bokeh", bokeh)
    monkeypatch.setitem(sys.modules, "bokeh.models", models)
    monkeypatch.setitem(sys.modules, "bokeh.plotting", plotting)
    yield
    G.clear()


def test_plot_static_table_fills_source_immediately(bokeh_stub):
    G.clear()
    t = pw.debug.table_from_markdown("""
    x | y
    1 | 10
    3 | 30
    2 | 20
    """)
    captured = {}

    def plotting_function(source):
        captured["source"] = source
        return FakeFigure()

    t.plot(plotting_function, sorting_col="x")
    src = captured["source"]
    assert src.data == {"x": [1, 2, 3], "y": [10, 20, 30]}
    assert src.streams[-1][1] == 3  # rollover == live row count


def test_plot_streaming_table_updates_source_per_tick(bokeh_stub):
    G.clear()

    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(x=1, y=10)
            time.sleep(0.3)
            self.next(x=2, y=20)
            time.sleep(2.0)

    t = pw.io.python.read(
        Subject(), schema=pw.schema_from_types(x=int, y=int),
        autocommit_duration_ms=30)
    captured = {}

    def plotting_function(source):
        captured["source"] = source
        return FakeFigure()

    t.plot(plotting_function, sorting_col="x")
    threading.Thread(target=lambda: pw.run(), daemon=True).start()
    src = captured["source"]
    deadline = time.time() + 10
    while time.time() < deadline and src.data.get("x") != [1, 2]:
        time.sleep(0.05)
    assert src.data == {"x": [1, 2], "y": [10, 20]}
    assert len(src.streams) >= 2  # one update per closed timestamp
    from pathway_tpu.engine import streaming

    streaming.stop_all()
