"""Azure Blob client + persistence backend against an in-test server that
VERIFIES the SharedKey signature (the Azure counterpart of
tests/test_s3.py — one object-per-commit snapshot log serves both)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.azure_blob import AzureBlobClient

ACCOUNT = "teststore"
KEY = base64.b64encode(b"super secret account key 123456").decode()


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


class _FakeAzure(BaseHTTPRequestHandler):
    blobs: dict = {}  # (container, name) -> bytes
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def _verify(self) -> bool:
        auth = self.headers.get("Authorization", "")
        if not auth.startswith(f"SharedKey {ACCOUNT}:"):
            return False
        got_sig = auth.split(":", 1)[1]
        u = urlparse(self.path)
        xms = sorted((k.lower(), v) for k, v in self.headers.items()
                     if k.lower().startswith("x-ms-"))
        canon_headers = "".join(f"{k}:{v}\n" for k, v in xms)
        canon_resource = f"/{ACCOUNT}{unquote(u.path)}"
        q = parse_qs(u.query)
        for k in sorted(q):
            canon_resource += f"\n{k}:{q[k][0]}"
        length = self.headers.get("Content-Length", "")
        if length == "0":
            length = ""
        string_to_sign = "\n".join([
            self.command, "", "", length, "",
            self.headers.get("Content-Type", ""),
            "", "", "", "", "", "",
        ]) + "\n" + canon_headers + canon_resource
        want = base64.b64encode(hmac.new(
            base64.b64decode(KEY), string_to_sign.encode(),
            hashlib.sha256).digest()).decode()
        return hmac.compare_digest(want, got_sig)

    def _reply(self, code, body=b""):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _split(self):
        u = urlparse(self.path)
        parts = unquote(u.path).lstrip("/").split("/", 1)
        return parts[0], parts[1] if len(parts) > 1 else "", parse_qs(u.query)

    def do_PUT(self):
        if not self._verify():
            return self._reply(403)
        container, name, _ = self._split()
        n = int(self.headers.get("Content-Length", 0))
        self.blobs[(container, name)] = self.rfile.read(n)
        self._reply(201)

    def do_GET(self):
        if not self._verify():
            return self._reply(403)
        container, name, q = self._split()
        if q.get("comp") == ["list"]:
            prefix = q.get("prefix", [""])[0]
            names = sorted(n for (c, n) in self.blobs
                           if c == container and n.startswith(prefix))
            xml = ["<?xml version='1.0'?><EnumerationResults><Blobs>"]
            for n in names:
                xml.append(
                    f"<Blob><Name>{n}</Name><Properties>"
                    f"<Content-Length>{len(self.blobs[(container, n)])}"
                    f"</Content-Length></Properties></Blob>")
            xml.append("</Blobs><NextMarker/></EnumerationResults>")
            return self._reply(200, "".join(xml).encode())
        data = self.blobs.get((container, name))
        if data is None:
            return self._reply(404)
        self._reply(200, data)

    def do_DELETE(self):
        if not self._verify():
            return self._reply(403)
        container, name, _ = self._split()
        self.blobs.pop((container, name), None)
        self._reply(202)


@pytest.fixture()
def fake_azure():
    _FakeAzure.blobs = {}
    server = ThreadingHTTPServer(("127.0.0.1", 0), _FakeAzure)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def _client(endpoint):
    return AzureBlobClient(account=ACCOUNT, container="snaps",
                           account_key=KEY, endpoint=endpoint)


def test_blob_roundtrip_signed(fake_azure):
    c = _client(fake_azure)
    c.put_object("a/x", b"hello")
    c.put_object("a/y", b"world")
    c.put_object("b/z", b"other")
    assert c.get_object("a/x") == b"hello"
    assert c.get_object_or_none("missing") is None
    assert [o["key"] for o in c.list_objects("a/")] == ["a/x", "a/y"]
    c.delete_object("a/x")
    assert c.get_object_or_none("a/x") is None


def test_blob_bad_key_rejected(fake_azure):
    bad = AzureBlobClient(
        account=ACCOUNT, container="snaps",
        account_key=base64.b64encode(b"wrong key").decode(),
        endpoint=fake_azure)
    with pytest.raises(RuntimeError, match="403"):
        bad.put_object("k", b"v")


def test_azure_persistence_backend_resume(fake_azure):
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io._datasource import Session
    from pathway_tpu.io.python import ConnectorSubject, PythonSource

    cfg = pw.persistence.Config(
        backend=pw.persistence.Backend.azure(
            "az://snaps/checkpoints",
            account=dict(account=ACCOUNT, account_key=KEY,
                         endpoint=fake_azure)))
    schema = sch.schema_from_types(data=str)

    class _Subject(ConnectorSubject):
        def run(self):
            pass

    src = PythonSource(_Subject(), schema)
    src.persistent_id = "events"
    driver = PersistenceDriver(cfg)
    live = Session()
    rec = driver.attach_source(src, live)
    k, r = src.row_to_engine({"data": "alpha"}, 0)
    rec.push(k, r, 1)
    driver.commit(1)
    driver.close()

    keys = [o["key"] for o in _client(fake_azure).list_objects("")]
    assert keys == ["checkpoints/streams/events/0000000000000000"]

    src2 = PythonSource(_Subject(), schema)
    src2.persistent_id = "events"
    driver2 = PersistenceDriver(cfg)
    live2 = Session()
    driver2.attach_source(src2, live2)
    assert [row[1][0] for row in live2.drain()] == ["alpha"]
    assert driver2.restore_time() == 1
    driver2.close()


def test_abfss_path_parsing():
    from pathway_tpu.io.azure_blob import client_from_backend

    backend = pw.persistence.Backend.azure(
        "abfss://snaps@myacct.dfs.core.windows.net/checkpoints",
        account=dict(account_key=KEY))
    client, prefix = client_from_backend(backend)
    assert client.container == "snaps"
    assert client.account == "myacct"
    assert client.base_url == "https://myacct.blob.core.windows.net"
    assert prefix == "checkpoints"


def test_azurite_path_style_signing(fake_azure):
    """Azurite carries the account in the URL path; the canonical resource
    must include it once from the endpoint and once as the account."""
    # the fake serves /{container}/... at the root, so emulate azurite by
    # checking only the signing shape here: base/path split is correct
    c = AzureBlobClient(account="devstoreaccount1", container="snaps",
                        account_key=KEY,
                        endpoint="http://127.0.0.1:10000/devstoreaccount1")
    assert c.base_url == "http://127.0.0.1:10000/devstoreaccount1"
    assert c._path_prefix == "/devstoreaccount1"
    headers: dict = {}
    c._sign("GET", "/snaps/blob", {}, headers)
    assert headers["Authorization"].startswith("SharedKey devstoreaccount1:")
