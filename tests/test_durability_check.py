"""Durability analyzer (static_check/durability_check.py): one
true-positive and one true-negative per PWT301–PWT308 code, the waiver
mechanism and its ``--list-waivers`` audit, the operator/fault-point
inventory, the engine+io dogfood gate, and the CLI front doors
(``--durability``, ``--all``, ``--list-waivers``) — mirrors
tests/test_concurrency_check.py for the PWT2xx family."""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
import textwrap

from pathway_tpu.internals.static_check import (check_durability,
                                                durability_inventory,
                                                scan_waivers)


def run_check(tmp_path, source: str):
    f = tmp_path / "mod_under_test.py"
    f.write_text(textwrap.dedent(source))
    return check_durability([str(f)])


def codes(diags):
    return sorted(d.code for d in diags)


def only(diags, code):
    return [d for d in diags if d.code == code]


# ---------------------------------------------------------------------------
# PWT301 — stateful operator with no snapshot/restore pair
# ---------------------------------------------------------------------------

_UNCOVERED_OPERATOR = """
    class RollingCountOperator:
        def __init__(self):
            self.counts = {}

        def step(self, key):
            self.counts[key] = self.counts.get(key, 0) + 1
"""


def test_pwt301_missing_pair_is_warning(tmp_path):
    diags = only(run_check(tmp_path, _UNCOVERED_OPERATOR), "PWT301")
    assert len(diags) == 1
    assert not diags[0].is_error  # degraded recovery, not wrong answers
    assert "counts" in diags[0].message
    assert "full-WAL replay" in diags[0].message


def test_pwt301_negative_local_pair(tmp_path):
    diags = run_check(tmp_path, """
        class RollingCountOperator:
            def __init__(self):
                self.counts = {}

            def step(self, key):
                self.counts[key] = self.counts.get(key, 0) + 1

            def snapshot_state(self):
                return {"counts": self.counts}

            def restore_state(self, state):
                self.counts = dict(state["counts"])
    """)
    assert only(diags, "PWT301") == []


def test_pwt301_negative_inherited_pair(tmp_path):
    diags = run_check(tmp_path, """
        class BaseWindowOperator:
            def snapshot_state(self):
                return {"buf": self.buf}

            def restore_state(self, state):
                self.buf = dict(state["buf"])

        class TumblingWindowOperator(BaseWindowOperator):
            def __init__(self):
                self.buf = {}

            def step(self, k, row):
                self.buf[k] = row
    """)
    assert only(diags, "PWT301") == []


def test_pwt301_negative_non_operator_class(tmp_path):
    # a plain cache class is outside the operator snapshot protocol
    diags = run_check(tmp_path, """
        class MetricsBag:
            def __init__(self):
                self.vals = {}

            def bump(self, k):
                self.vals[k] = self.vals.get(k, 0) + 1
    """)
    assert only(diags, "PWT301") == []


# ---------------------------------------------------------------------------
# PWT302 — capture/restore key asymmetry
# ---------------------------------------------------------------------------

def test_pwt302_captured_key_never_restored(tmp_path):
    diags = only(run_check(tmp_path, """
        class BufferOperator:
            def __init__(self):
                self.held = {}
                self.seen = set()

            def snapshot_state(self):
                return {"held": self.held, "seen": self.seen}

            def restore_state(self, state):
                self.held = dict(state["held"])
    """), "PWT302")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "'seen'" in diags[0].message
    assert "lost on recovery" in diags[0].message


def test_pwt302_restored_key_never_captured(tmp_path):
    diags = only(run_check(tmp_path, """
        class BufferOperator:
            def __init__(self):
                self.held = {}

            def snapshot_state(self):
                return {"held": self.held}

            def restore_state(self, state):
                self.held = dict(state["held"])
                self.wm = state["watermark"]
    """), "PWT302")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "'watermark'" in diags[0].message


def test_pwt302_negative_symmetric_keys(tmp_path):
    diags = run_check(tmp_path, """
        class BufferOperator:
            def __init__(self):
                self.held = {}
                self.seen = set()

            def snapshot_state(self):
                st: dict = {"held": self.held}
                st["seen"] = sorted(self.seen)
                return st

            def restore_state(self, state):
                self.held = dict(state["held"])
                if "seen" in state:
                    self.seen = set(state["seen"])
    """)
    assert only(diags, "PWT302") == []


def test_pwt302_negative_dynamic_restore_is_open(tmp_path):
    # a restore that iterates the whole state dict may read any key:
    # the "captured but never restored" direction cannot be claimed
    diags = run_check(tmp_path, """
        class BufferOperator:
            def __init__(self):
                self.held = {}
                self.seen = set()

            def snapshot_state(self):
                return {"held": self.held, "seen": self.seen}

            def restore_state(self, state):
                for key, value in state.items():
                    setattr(self, key, value)
    """)
    assert only(diags, "PWT302") == []


# ---------------------------------------------------------------------------
# PWT303 — volatile-keyed snapshot state with no re-key on restore
# ---------------------------------------------------------------------------

_VOLATILE_KEYED = """
    class DedupOperator:
        def __init__(self):
            self.held = {}

        def step(self, key, row):
            fp = row_fingerprint(row)
            self.held[(key, fp)] = row

        def snapshot_state(self):
            return {"held": self.held}

        def restore_state(self, state):
            self.held = dict(state["held"])
"""


def test_pwt303_volatile_keys_without_rekey(tmp_path):
    diags = only(run_check(tmp_path, _VOLATILE_KEYED), "PWT303")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "held" in diags[0].message
    assert "re-key" in diags[0].message


def test_pwt303_negative_rekeyed_on_restore(tmp_path):
    diags = run_check(tmp_path, """
        class DedupOperator:
            def __init__(self):
                self.held = {}

            def step(self, key, row):
                fp = row_fingerprint(row)
                self.held[(key, fp)] = row

            def snapshot_state(self):
                return {"held": self.held}

            def restore_state(self, state):
                self.held = {(k, row_fingerprint(r)): r
                             for (k, _), r in state["held"].items()}
    """)
    assert only(diags, "PWT303") == []


def test_pwt303_negative_stable_keys(tmp_path):
    # _stable_row_fp is a content digest — stable keys need no re-key
    diags = run_check(tmp_path, """
        class DedupOperator:
            def __init__(self):
                self.held = {}

            def step(self, key, row):
                fp = _stable_row_fp(row)
                self.held[(key, fp)] = row

            def snapshot_state(self):
                return {"held": self.held}

            def restore_state(self, state):
                self.held = dict(state["held"])
    """)
    assert only(diags, "PWT303") == []


# ---------------------------------------------------------------------------
# PWT304 — persistence-path write outside tmp+fsync+rename
# ---------------------------------------------------------------------------

def test_pwt304_torn_write_on_persistence_path(tmp_path):
    diags = only(run_check(tmp_path, """
        import json

        def save_manifest(root, manifest):
            with open(root / "manifest.json", "w") as f:
                f.write(json.dumps(manifest))
    """), "PWT304")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "tmp+fsync+rename" in diags[0].message


def test_pwt304_write_text_on_snapshot_path(tmp_path):
    diags = only(run_check(tmp_path, """
        def write_gen(snapshot_dir, payload):
            (snapshot_dir / "gen-7.json").write_text(payload)
    """), "PWT304")
    assert len(diags) == 1


def test_pwt304_negative_atomic_discipline(tmp_path):
    # the enclosing function implements tmp+fsync+rename itself
    diags = run_check(tmp_path, """
        import os

        def save_manifest(root, payload):
            tmp = root / "manifest.json.tmp"
            with open(tmp, "w") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, root / "manifest.json")
    """)
    assert only(diags, "PWT304") == []


def test_pwt304_negative_non_persistence_path(tmp_path):
    diags = run_check(tmp_path, """
        def dump_debug(out_dir, payload):
            with open(out_dir / "debug.csv", "w") as f:
                f.write(payload)
    """)
    assert only(diags, "PWT304") == []


# ---------------------------------------------------------------------------
# PWT305 — blocking persistence I/O with no named fault point
# ---------------------------------------------------------------------------

def test_pwt305_fsync_without_fault_point(tmp_path):
    diags = only(run_check(tmp_path, """
        import os

        def flush_log(f):
            f.flush()
            os.fsync(f.fileno())
    """), "PWT305")
    assert len(diags) == 1
    assert not diags[0].is_error
    assert "fault point" in diags[0].message


def test_pwt305_negative_named_fault_point(tmp_path):
    diags = run_check(tmp_path, """
        import os

        from pathway_tpu.testing import faults

        def flush_log(f):
            f.flush()
            faults.hit("wal.fsync")
            os.fsync(f.fileno())
    """)
    assert only(diags, "PWT305") == []


# ---------------------------------------------------------------------------
# PWT306 — unrestricted pickle on a restore path
# ---------------------------------------------------------------------------

def test_pwt306_raw_pickle_loads(tmp_path):
    diags = only(run_check(tmp_path, """
        import pickle

        def load_snapshot(blob):
            return pickle.loads(blob)
    """), "PWT306")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "_safe_loads" in diags[0].message


def test_pwt306_negative_safe_loads(tmp_path):
    diags = run_check(tmp_path, """
        from pathway_tpu.engine.persistence import _safe_loads

        def load_snapshot(blob):
            return _safe_loads(blob)
    """)
    assert only(diags, "PWT306") == []


# ---------------------------------------------------------------------------
# PWT307 — Session.drain outside seal_drain
# ---------------------------------------------------------------------------

def test_pwt307_unsealed_drain(tmp_path):
    diags = only(run_check(tmp_path, """
        def pump(session, limit):
            return session.drain(limit)
    """), "PWT307")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "seal_drain" in diags[0].message


def test_pwt307_negative_seal_drain_provider(tmp_path):
    # the atomic helper itself, and the provider class's delegation
    diags = run_check(tmp_path, """
        class Recorder:
            def seal_drain(self, tick, limit):
                rows = self.session.drain(limit)
                self._seal(tick, rows)
                return rows

            def _flush(self, tick, limit):
                return self.session.drain(limit)
    """)
    assert only(diags, "PWT307") == []


def test_pwt307_negative_non_session_receiver(tmp_path):
    diags = run_check(tmp_path, """
        def pump(queue, limit):
            return queue.drain(limit)
    """)
    assert only(diags, "PWT307") == []


# ---------------------------------------------------------------------------
# PWT308 — nondeterminism feeding snapshotted state
# ---------------------------------------------------------------------------

def test_pwt308_wallclock_into_snapshotted_attr(tmp_path):
    diags = only(run_check(tmp_path, """
        import time

        class StampOperator:
            def __init__(self):
                self.latest = {}

            def step(self, key):
                self.latest[key] = time.time()

            def snapshot_state(self):
                return {"latest": self.latest}

            def restore_state(self, state):
                self.latest = dict(state["latest"])
    """), "PWT308")
    assert len(diags) == 1
    assert not diags[0].is_error
    assert "diverge" in diags[0].message


def test_pwt308_negative_uncaptured_scratch(tmp_path):
    # wall-clock into an attr the snapshot never captures is fine
    diags = run_check(tmp_path, """
        import time

        class StampOperator:
            def __init__(self):
                self.latest = {}
                self._last_poll = 0.0

            def step(self, key):
                self._last_poll = time.time()
                self.latest[key] = key

            def snapshot_state(self):
                return {"latest": self.latest}

            def restore_state(self, state):
                self.latest = dict(state["latest"])
    """)
    assert only(diags, "PWT308") == []


# ---------------------------------------------------------------------------
# waivers
# ---------------------------------------------------------------------------

def test_waiver_suppresses_named_code(tmp_path):
    diags = run_check(tmp_path, """
        import pickle

        def load_frame(blob):
            # pwt-ok: PWT306 — trusted intra-process test fixture
            return pickle.loads(blob)
    """)
    assert only(diags, "PWT306") == []


def test_waiver_for_other_code_does_not_suppress(tmp_path):
    diags = run_check(tmp_path, """
        import pickle

        def load_frame(blob):
            # pwt-ok: PWT305 — wrong family
            return pickle.loads(blob)
    """)
    assert len(only(diags, "PWT306")) == 1


def test_scan_waivers_reports_codes_and_justification(tmp_path):
    f = tmp_path / "mod_under_test.py"
    f.write_text(textwrap.dedent("""
        def load_frame(blob):
            # pwt-ok: PWT306 — trusted fixture,
            # never fed external bytes
            return pickle.loads(blob)

        def anything(x):
            return x  # pwt-ok
    """))
    waivers = scan_waivers([str(f)])
    assert [w["codes"] for w in waivers] == [["PWT306"], ["*"]]
    assert waivers[0]["comment"] == \
        "trusted fixture, never fed external bytes"
    assert waivers[0]["line"] == 3


def test_scan_waivers_ignores_strings_and_docstrings(tmp_path):
    f = tmp_path / "mod_under_test.py"
    f.write_text(textwrap.dedent('''
        """Docs: suppress a finding with ``# pwt-ok: PWT306 — reason``."""

        HELP = "list every pwt-ok waiver under the given paths"

        def real(blob):
            # pwt-ok: PWT306 — the only genuine marker in this module
            return pickle.loads(blob)
    '''))
    waivers = scan_waivers([str(f)])
    assert [w["line"] for w in waivers] == [7]
    assert waivers[0]["codes"] == ["PWT306"]


# ---------------------------------------------------------------------------
# inventory
# ---------------------------------------------------------------------------

def test_inventory_operators_and_fault_points(tmp_path):
    inv = durability_inventory(["pathway_tpu/engine"])
    by_class = {o["class"]: o for o in inv["operators"]}
    assert by_class["JoinOperator"]["has_snapshot_pair"]
    assert "persistence.atomic.replace" in inv["fault_points"]
    assert "fs.atomic_write.replace" in inv["fault_points"]
    assert "observability.history.append" in inv["fault_points"]


# ---------------------------------------------------------------------------
# dogfood gates — the persistence plane itself must pass its own lint
# ---------------------------------------------------------------------------

def test_engine_source_is_durability_clean():
    assert check_durability(["pathway_tpu/engine"]) == []


def test_io_source_is_durability_clean():
    assert check_durability(["pathway_tpu/io"]) == []


def test_seeded_negative_example_trips_the_gate():
    diags = check_durability(["tests/durability_negative_example.py"])
    assert any(d.code == "PWT301" for d in diags)
    assert any(d.code == "PWT304" and d.is_error for d in diags)


# ---------------------------------------------------------------------------
# CLI front doors
# ---------------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu", "check", *args],
        capture_output=True, text=True, env=None)


def test_cli_durability_clean_and_json():
    proc = _run_cli("--durability", "--json", "pathway_tpu/engine")
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["diagnostics"] == []
    assert "persistence.atomic.replace" in \
        payload["inventory"]["fault_points"]


def test_cli_durability_seeded_negative_fails():
    proc = _run_cli("--durability",
                    "tests/durability_negative_example.py")
    assert proc.returncode == 1
    assert "PWT304" in proc.stdout


def test_cli_all_clean_tree_and_schema(tmp_path):
    proc = _run_cli("--all", "--json", "pathway_tpu/engine")
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["schema_version"] == 2
    assert set(payload["families"]) == \
        {"expression", "shard", "concurrency", "durability", "perf"}
    assert payload["exit_code"] == 0


def test_cli_all_exit_code_is_family_bitmask(tmp_path):
    tree = tmp_path / "src"
    tree.mkdir()
    shutil.copy("tests/durability_negative_example.py",
                tree / "negative.py")
    proc = _run_cli("--all", "--json", str(tree))
    assert proc.returncode == 8, proc.stderr  # durability bit only
    payload = json.loads(proc.stdout)
    assert payload["exit_code"] == 8
    fam_codes = [d["code"] for d in payload["families"]["durability"]]
    assert "PWT304" in fam_codes


def test_cli_list_waivers_json_audit():
    proc = _run_cli("--list-waivers", "--json", "pathway_tpu/engine")
    assert proc.returncode == 0, proc.stderr
    waivers = json.loads(proc.stdout)
    wire = [w for w in waivers if w["file"].endswith("wire.py")]
    assert wire and all(w["codes"] == ["PWT306"] for w in wire)
    assert all(w["comment"] for w in wire)  # every waiver justified


def test_cli_modes_are_mutually_exclusive():
    proc = _run_cli("--concurrency", "--durability", "pathway_tpu/engine")
    assert proc.returncode != 0
    assert "mutually exclusive" in proc.stderr
