"""AsyncTransformer semantics (reference:
python/pathway/stdlib/utils/async_transformer.py:61-490): status column,
successful/failed/finished views, instance-consistency demotion,
with_options, signature validation."""

from __future__ import annotations

import asyncio

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from tests.utils import T, rows_of


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


class OutSchema(pw.Schema):
    ret: int


def _input():
    return T("""
    value | group
    1     | a
    2     | a
    3     | b
    """)


def test_successful_basic():
    class Inc(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value, group) -> dict:
            await asyncio.sleep(0.001)
            return {"ret": value + 1}

    res = Inc(input_table=_input()).successful
    assert sorted(rows_of(res)) == [(2,), (3,), (4,)]


def test_failure_rows_and_status_column():
    class Flaky(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value, group) -> dict:
            if value == 2:
                raise RuntimeError("boom")
            return {"ret": value * 10}

    tr = Flaky(input_table=_input())
    assert sorted(rows_of(tr.successful)) == [(10,), (30,)]
    assert sorted(rows_of(tr.failed)) == [(None,)]
    statuses = sorted(s for _, s in rows_of(tr.output_table))
    assert statuses == ["-FAILURE-", "-SUCCESS-", "-SUCCESS-"]
    # finished == output_table under BSP execution
    assert sorted(rows_of(tr.finished), key=repr) == sorted(
        rows_of(tr.output_table), key=repr)


def test_instance_failure_demotes_group():
    t = _input()

    class Flaky(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value, group) -> dict:
            if value == 1:
                raise RuntimeError("boom")
            return {"ret": value * 10}

    tr = Flaky(input_table=t, instance=t.group)
    # value=2 succeeded but shares instance 'a' with the failed value=1:
    # demoted (reference _Instance.correct); only 'b' survives
    assert sorted(rows_of(tr.successful)) == [(30,)]
    assert sorted(rows_of(tr.failed)) == [(None,), (None,)]


def test_wrong_result_keys_is_failure():
    class Bad(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value, group) -> dict:
            return {"wrong": 1}

    tr = Bad(input_table=_input())
    assert rows_of(tr.successful) == []
    assert len(rows_of(tr.failed)) == 3


def test_signature_validation():
    class Inc(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:  # missing 'group'
            return {"ret": value}

    with pytest.raises(TypeError, match="not present"):
        Inc(input_table=_input())

    class Missing(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value, group, extra) -> dict:
            return {"ret": value}

    with pytest.raises(TypeError, match="not a column"):
        Missing(input_table=_input())


def test_with_options_retry():
    attempts: dict[int, int] = {}

    class FlakyOnce(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value, group) -> dict:
            attempts[value] = attempts.get(value, 0) + 1
            if attempts[value] == 1:
                raise RuntimeError("transient")
            return {"ret": value}

    tr = FlakyOnce(input_table=_input()).with_options(
        retry_strategy=pw.udfs.FixedDelayRetryStrategy(
            max_retries=3, delay_ms=1))
    assert sorted(rows_of(tr.successful)) == [(1,), (2,), (3,)]
    assert all(n >= 2 for n in attempts.values())


def test_missing_output_schema_raises():
    class NoSchema(pw.AsyncTransformer):
        async def invoke(self, value, group) -> dict:
            return {}

    with pytest.raises(TypeError, match="output_schema"):
        NoSchema(input_table=_input())
