"""Elastic replica fleet: snapshot-hydrated read replicas + the
latency-aware router (engine/replica.py, engine/router.py).

Covers the PR's pinned contracts:

* read-only persistence open mode — a replica can never append to,
  truncate, compact or snapshot the primary's root; violations raise
  ``ReadOnlyPersistenceError`` BY NAME;
* incremental WAL tailing — torn tails are retried (never dropped),
  compaction rescans deduplicate by record tick;
* hydration equivalence — a replica hydrated at generation G + WAL
  suffix answers ``query_as_of_now`` byte-identically to the primary at
  the same applied tick, swept across snapshot boundaries (no snapshot /
  snapshot-covers-all / snapshot + suffix) and the
  corrupt-newest-generation fallback;
* live tailing — a replica trailing a RUNNING primary converges to
  staleness 0 and exports role/applied_tick/staleness on /status,
  /healthz and /metrics;
* router policy — staleness bound + latency-aware least-work choice,
  replica-before-primary preference, deterministic failover (dead
  endpoint chosen first, query survives), burn-rate-driven scale out/in
  over the control channel.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine import streaming as _streaming
from pathway_tpu.engine.multiproc import (control_authkey, hmac_handshake,
                                          recv_control_frame,
                                          send_control_frame)
from pathway_tpu.engine.persistence import (PersistenceDriver,
                                            ReadOnlyPersistenceError,
                                            SnapshotLog, scan_log_bytes)
from pathway_tpu.engine.replica import _FsLogTail
from pathway_tpu.engine.router import (NoReplicaAvailable, QueryRouter,
                                       ReplicaEndpoint)
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.http import PathwayWebserver, rest_connector
from pathway_tpu.io.python import ConnectorSubject
from pathway_tpu.stdlib.indexing import default_brute_force_knn_document_index

DIM = 8


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()
    _streaming.stop_all()


def _fs_config(root):
    return pw.persistence.Config(
        backend=pw.persistence.Backend.filesystem(str(root)))


# ---------------------------------------------------------------------------
# read-only open mode (test-pinned satellite)
# ---------------------------------------------------------------------------

def test_readonly_driver_raises_by_name(tmp_path):
    # a primary writes some history first
    rw = PersistenceDriver(_fs_config(tmp_path))
    log = rw._log_for("src")
    log.append(1, [("k", ("row",), 1, None)])
    log.close()

    ro = PersistenceDriver(_fs_config(tmp_path), read_only=True)
    assert ro.read_only
    # reads pass through
    assert ro.restore_time() == 1
    assert ro.list_source_ids() == ["src"]
    assert ro._records("src")[0][0] == 1
    # every mutation raises BY NAME
    with pytest.raises(ReadOnlyPersistenceError):
        ro.commit(2)
    with pytest.raises(ReadOnlyPersistenceError):
        ro.write_snapshot(2, {"nodes": {}})
    with pytest.raises(ReadOnlyPersistenceError):
        ro._compact()

    class _FakeSource:
        persistent_id = "src"
        name = "fake"
        _uid = 0

    with pytest.raises(ReadOnlyPersistenceError):
        ro.attach_source(_FakeSource(), object())
    # and the log proxy itself refuses (defense in depth)
    rolog = ro._log_for("src")
    with pytest.raises(ReadOnlyPersistenceError):
        rolog.append(3, [])
    with pytest.raises(ReadOnlyPersistenceError):
        rolog.truncate_to(1)
    assert rolog.read_all()[0][0] == 1


def test_readonly_driver_does_not_create_dirs(tmp_path):
    root = tmp_path / "never_written"
    ro = PersistenceDriver(_fs_config(root), read_only=True)
    assert ro.list_source_ids() == []
    assert ro.restore_time() == 0
    assert not root.exists(), "read-only open must not touch the disk"


# ---------------------------------------------------------------------------
# WAL tailing primitives
# ---------------------------------------------------------------------------

def test_scan_log_bytes_leaves_torn_tail_unconsumed(tmp_path):
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("a", ("r",), 1, None)])
    log.append(2, [("b", ("r",), 1, None)])
    log.close()
    data = open(path, "rb").read()
    # whole image parses
    recs, consumed = scan_log_bytes(data, expect_magic=True)
    assert [t for t, _ in recs] == [1, 2] and consumed == len(data)
    # truncated mid-record: the second record is left unconsumed
    recs, consumed = scan_log_bytes(data[:-3], expect_magic=True)
    assert [t for t, _ in recs] == [1]
    assert consumed < len(data) - 3
    # the unconsumed suffix completes once the remaining bytes land
    recs2, c2 = scan_log_bytes(data[consumed:], expect_magic=False)
    assert [t for t, _ in recs2] == [2] and consumed + c2 == len(data)


def test_fs_tail_torn_record_reports_no_progress(tmp_path):
    """A torn tail record re-read on every poll must report 0 bytes of
    progress — otherwise the quiet-poll release in pump() never fires
    and a crashed primary's final complete tick is withheld forever."""
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("a", ("r",), 1, None)])
    log.append(2, [("b", ("r",), 1, None)])
    log.close()
    whole = open(path, "rb").read()
    tail = _FsLogTail(path)
    recs, consumed = tail.poll()
    assert [t for t, _ in recs] == [1, 2] and consumed == len(whole)
    # primary crashes mid-append: a torn third record sits at the tail
    with open(path, "ab") as f:
        f.write(b"\x99" * 7)
    for _ in range(3):  # every poll: no records, NO progress
        assert tail.poll() == ([], 0)
    # the record completing later resumes normal progress
    os.truncate(path, len(whole))
    log2 = SnapshotLog(path)
    log2.append(3, [("c", ("r",), 1, None)])
    log2.close()
    recs, consumed = tail.poll()
    assert [t for t, _ in recs] == [3] and consumed > 0


def test_pump_raises_when_compaction_outruns_tail(tmp_path):
    """If the primary compacts its WAL past a lagging replica's tail
    position, the dropped records are unrecoverable — pump must die
    loudly (restart re-hydrates from the newest generation) instead of
    silently serving a gapped state."""
    from pathway_tpu.engine.replica import ReplicaHydrationError, \
        ReplicaTailer

    root = tmp_path / "root"
    (root / "streams").mkdir(parents=True)
    path = str(root / "streams" / "s.snap")
    log = SnapshotLog(path)
    for t in range(1, 5):
        log.append(t, [(f"k{t}", ("r",), 1, None)])
    tailer = ReplicaTailer(str(root), replica_id="gap-test")
    tail = _FsLogTail(path)
    tailer._tails = {"s": tail}
    recs, _ = tail.poll()
    assert tail.last_tick == 4

    class _Rt:  # pump touches the scheduler only when batches apply
        scheduler = None

    tailer._pending.clear()  # seen-but-unapplied is not lost
    # compaction drops ticks <= 4 while the tail is CAUGHT UP: fine
    log.truncate_to(4)
    log.append(5, [("k5", ("r",), 1, None)])
    tailer.driver.oldest_snapshot_tick = lambda: 4
    # the rescan is noticed, the gap check passes (last_tick 4 >= floor
    # 4), and the newest tick 5 is held back — no raise, no apply
    assert tailer.pump(_Rt(), 100) == 100
    assert tail.last_tick == 5
    # now the tail LAGS: a fresh tail that never saw ticks 1..5 meets a
    # log whose floor is 5 — the gap is real, the tailer must refuse
    log.truncate_to(5)
    log.append(6, [("k6", ("r",), 1, None)])
    log.close()
    lagging = _FsLogTail(path)
    lagging.poll()
    lagging._ino = -1  # next poll sees a "changed" inode -> rescan
    lagging.last_tick = 2  # saw only ticks <= 2 before the compaction
    tailer._tails = {"s": lagging}
    tailer._pending.clear()
    tailer.driver.oldest_snapshot_tick = lambda: 5
    with pytest.raises(ReplicaHydrationError, match="compacted"):
        tailer.pump(_Rt(), 101)


def test_fs_tail_incremental_and_dedup(tmp_path):
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    tail = _FsLogTail(path)
    assert tail.poll() == ([], 0)  # no file yet
    log.append(1, [("a", ("r",), 1, None)])
    recs, nbytes = tail.poll()
    assert [t for t, _ in recs] == [1] and nbytes > 0
    assert tail.poll() == ([], 0)  # nothing new
    log.append(2, [("b", ("r",), 1, None)])
    log.append(3, [("c", ("r",), 1, None)])
    recs, _ = tail.poll()
    assert [t for t, _ in recs] == [2, 3]
    # compaction: atomic rewrite dropping records <= 2 (new inode) —
    # the rescan must not re-deliver tick 3
    log.truncate_to(2)
    assert tail.poll() == ([], 0) or tail.poll()[0] == []
    log.append(4, [("d", ("r",), 1, None)])
    recs, _ = tail.poll()
    assert [t for t, _ in recs] == [4]
    log.close()


# ---------------------------------------------------------------------------
# hydration equivalence (query_as_of_now byte-identity)
# ---------------------------------------------------------------------------

def _build_knn_app(n_vecs, ws, *, trickle=False):
    """The shared primary/replica program: seeded vector feed -> KNN
    index -> rest route answering query_as_of_now with (ids, scores)."""

    class Subject(ConnectorSubject):
        def run(self):
            rng = np.random.default_rng(7)
            for i in range(n_vecs):
                v = rng.random(DIM, np.float32) * 2 - 1
                self.next(v=v)
                if i % 16 == 15 or trickle:
                    if not self._session.sleep(0.05 if not trickle
                                               else 0.02):
                        return

    data = pw.io.python.read(
        Subject(), schema=sch.schema_from_types(v=np.ndarray),
        autocommit_duration_ms=20, name="vecs", persistent_id="vecs")
    index = default_brute_force_knn_document_index(
        data.v, data, dimensions=DIM, reserved_space=512)
    qschema = sch.schema_from_types(vec=dt.ANY, k=int)
    queries, writer = rest_connector(
        webserver=ws, route="/q", schema=qschema, methods=("POST",),
        delete_completed_queries=True, autocommit_duration_ms=10)
    qv = queries.select(
        qv=pw.apply(lambda v: np.asarray(v, dtype=np.float32),
                    queries.vec),
        k=queries.k)
    res = index.query_as_of_now(qv.qv, number_of_matches=qv.k)
    writer(res.select(
        ids=pw.apply(lambda ids: [str(i) for i in ids],
                     res._pw_index_reply_id),
        scores=pw.apply(lambda ds: [float(d) for d in ds],
                        res._pw_index_reply_score)))


def _run_bg(**kw):
    errs: list[BaseException] = []

    def _r():
        try:
            pw.run(**kw)
        except Exception as e:  # noqa: BLE001 — surfaced by the test
            errs.append(e)

    th = threading.Thread(target=_r, daemon=True)
    th.start()
    return th, errs


def _wait_runtime(ws, errs, *, replica=None, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if errs:
            raise AssertionError(f"pipeline failed: {errs[0]!r}")
        for rt in list(_streaming._ACTIVE_RUNTIMES):
            if replica is not None and (rt.replica is not None) != replica:
                continue
            if ws._started.is_set() and ws.port:
                return rt
        time.sleep(0.05)
    raise TimeoutError("runtime never started")


def _ask(port, vec, k=5):
    body = json.dumps({"vec": [float(x) for x in vec], "k": k}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/q", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.read().decode()


def _run_primary(root, n_vecs, qvecs, monkeypatch, *,
                 snapshot_ticks=0, expect_new=None) -> list[str]:
    """Run the app as primary over ``root``, wait until all vectors are
    durable, capture the reference answers, stop cleanly.
    ``expect_new`` is the number of entries this run commits itself (a
    restart replays the durable prefix, which does not re-commit)."""
    G.clear()
    if snapshot_ticks:
        monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS",
                           str(snapshot_ticks))
    else:
        monkeypatch.delenv("PATHWAY_SNAPSHOT_EVERY_TICKS", raising=False)
    ws = PathwayWebserver(host="127.0.0.1", port=0)
    _build_knn_app(n_vecs, ws)
    th, errs = _run_bg(persistence_config=_fs_config(root))
    rt = _wait_runtime(ws, errs, replica=False)
    want = n_vecs if expect_new is None else expect_new
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline \
            and rt.persistence.entries_committed < want:
        time.sleep(0.05)
    assert rt.persistence.entries_committed >= want, \
        rt.persistence.stats()
    answers = [_ask(ws.port, q) for q in qvecs]
    _streaming.stop_all()
    th.join(timeout=30)
    assert not th.is_alive() and not errs, errs
    return answers


def _run_replica_and_answer(root, n_vecs, qvecs, expect_entries=None):
    """Start the same program as a replica of ``root``, wait for
    catch-up, answer the query set, return (answers, tailer stats)."""
    G.clear()
    ws = PathwayWebserver(host="127.0.0.1", port=0)
    _build_knn_app(n_vecs, ws)
    th, errs = _run_bg(replica_of=str(root))
    rt = _wait_runtime(ws, errs, replica=True)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = rt.replica.stats()
        if st["applied_tick"] == st["primary_watermark"] and (
                expect_entries is None
                or st["entries_applied"] >= expect_entries):
            break
        time.sleep(0.05)
    stats = rt.replica.stats()
    answers = [_ask(ws.port, q) for q in qvecs]
    _streaming.stop_all()
    th.join(timeout=30)
    assert not th.is_alive() and not errs, errs
    return answers, stats


_QVECS = np.random.default_rng(3).random((4, DIM), np.float32) * 2 - 1


def test_hydration_equivalence_wal_only(tmp_path, monkeypatch):
    """No snapshot generation at all: the replica replays the whole WAL
    through the tail path and answers byte-identically."""
    primary = _run_primary(tmp_path, 48, _QVECS, monkeypatch,
                           snapshot_ticks=0)
    replica, st = _run_replica_and_answer(tmp_path, 48, _QVECS,
                                          expect_entries=48)
    assert replica == primary
    assert st["generation"] == 0 and st["entries_applied"] >= 48


def test_hydration_equivalence_snapshot_covers_all(tmp_path, monkeypatch):
    """Teardown snapshot covers the full history: hydration is pure
    state restore (KNN re-upload), zero WAL entries replayed."""
    primary = _run_primary(tmp_path, 48, _QVECS, monkeypatch,
                           snapshot_ticks=4)
    replica, st = _run_replica_and_answer(tmp_path, 48, _QVECS)
    assert replica == primary
    assert st["generation"] >= 1
    assert st["entries_applied"] == 0  # the snapshot covered everything


def test_hydration_equivalence_snapshot_plus_suffix(tmp_path, monkeypatch):
    """Generation G + a genuine WAL suffix: phase 2 extends the history
    with snapshots disabled, so the replica must restore G and tail the
    suffix past it."""
    _run_primary(tmp_path, 32, _QVECS, monkeypatch, snapshot_ticks=4)
    primary = _run_primary(tmp_path, 56, _QVECS, monkeypatch,
                           snapshot_ticks=0,  # +24 vecs, WAL-only
                           expect_new=24)
    replica, st = _run_replica_and_answer(tmp_path, 56, _QVECS,
                                          expect_entries=1)
    assert replica == primary
    assert st["generation"] >= 1, "must hydrate from the snapshot"
    assert st["entries_applied"] >= 24, "must tail the WAL suffix"


def test_hydration_equivalence_corrupt_newest_generation(
        tmp_path, monkeypatch):
    """A corrupt newest generation falls back one generation and replays
    a longer suffix — answers stay byte-identical (the WAL retains the
    suffix back to the OLDEST kept generation). The newest generation is
    corrupted BEFORE a WAL-only extension run, so the replica must both
    fall back and tail genuine data records past the fallback."""
    _run_primary(tmp_path, 48, _QVECS, monkeypatch, snapshot_ticks=3)
    snapdir = tmp_path / "snapshots"
    states = sorted(snapdir.glob("*.state"))
    assert len(states) >= 2, "need >= 2 generations for the fallback"
    blob = bytearray(states[-1].read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    states[-1].write_bytes(bytes(blob))
    # extension run: the primary itself falls back (loudly), then grows
    # the history WAL-only — no fresh generation shadows the corruption
    primary = _run_primary(tmp_path, 56, _QVECS, monkeypatch,
                           snapshot_ticks=0, expect_new=8)
    replica, st = _run_replica_and_answer(tmp_path, 56, _QVECS,
                                          expect_entries=8)
    assert replica == primary
    newest = int(states[-1].stem)
    assert 1 <= st["generation"] < newest, \
        f"expected fallback below generation {newest}, got {st}"
    assert st["entries_applied"] >= 8, "fallback must replay the suffix"


def test_promotion_hydration_equivalence_and_idempotence(
        tmp_path, monkeypatch):
    """The failover tentpole, single-process: a replica of a dead
    primary's root is PROMOTED in place — it finishes tailing, fences
    the root at the election epoch, flips to role=primary with a
    writable driver, and answers byte-identically to the primary it
    replaced. A duplicate promote frame is a no-op (the router may
    re-send after a control partition)."""
    primary = _run_primary(tmp_path, 48, _QVECS, monkeypatch,
                           snapshot_ticks=0)
    G.clear()
    ws = PathwayWebserver(host="127.0.0.1", port=0)
    _build_knn_app(48, ws)
    th, errs = _run_bg(replica_of=str(tmp_path))
    rt = _wait_runtime(ws, errs, replica=True)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = rt.replica.stats()
        if st["applied_tick"] == st["primary_watermark"] \
                and st["entries_applied"] >= 48:
            break
        time.sleep(0.05)
    assert rt.role == "replica"

    rt.request_promotion({"epoch": 1, "dead": "p0"})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and rt.role != "primary":
        assert not errs, errs
        time.sleep(0.05)
    assert rt.role == "primary"
    assert rt.promotions == 1
    assert rt.promotion_tick is not None
    assert rt.failover_promotion_s is not None
    # the promoted runtime owns a WRITABLE driver at the claimed epoch
    assert rt.persistence is not None and not rt.persistence.read_only
    assert rt.persistence.fencing_epoch >= 1
    # byte-identical serving across the promotion
    promoted = [_ask(ws.port, q) for q in _QVECS]
    assert promoted == primary
    # duplicate promote frame: absorbed without a second epoch bump
    rt.request_promotion({"epoch": 2, "dead": "p0"})
    time.sleep(0.5)
    assert not errs, errs
    assert rt.promotions == 1
    assert rt.persistence.fencing_epoch == 1
    assert [_ask(ws.port, q) for q in _QVECS] == primary
    _streaming.stop_all()
    th.join(timeout=30)
    assert not th.is_alive() and not errs, errs


def test_replica_live_tail_staleness_and_surfaces(tmp_path, monkeypatch):
    """A replica trailing a RUNNING primary: applied tick advances while
    the primary ingests, converges to staleness 0, and the role /
    applied_tick / staleness fields + the staleness metric family are
    live on the replica's own monitoring endpoint."""
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "8")
    monkeypatch.setenv("PATHWAY_MONITORING_HTTP_PORT", "0")
    n = 120
    G.clear()
    ws_p = PathwayWebserver(host="127.0.0.1", port=0)
    _build_knn_app(n, ws_p, trickle=True)
    th_p, errs_p = _run_bg(persistence_config=_fs_config(tmp_path))
    rt_p = _wait_runtime(ws_p, errs_p, replica=False)
    # let some history accumulate, then hydrate a replica mid-stream
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline \
            and rt_p.persistence.entries_committed < n // 4:
        time.sleep(0.05)
    monkeypatch.delenv("PATHWAY_SNAPSHOT_EVERY_TICKS", raising=False)
    G.clear()
    ws_r = PathwayWebserver(host="127.0.0.1", port=0)
    _build_knn_app(n, ws_r)
    th_r, errs_r = _run_bg(replica_of=str(tmp_path),
                           with_http_server=True)
    rt_r = _wait_runtime(ws_r, errs_r, replica=True)
    mid_applied = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if errs_p or errs_r:
            raise AssertionError((errs_p, errs_r))
        st = rt_r.replica.stats()
        if mid_applied is None and st["entries_applied"] > 0:
            mid_applied = st["applied_tick"]
        if rt_p.persistence.entries_committed >= n \
                and st["entries_applied"] + 0 >= 0 \
                and st["applied_tick"] == st["primary_watermark"] \
                and st["primary_watermark"] > 0:
            break
        time.sleep(0.05)
    st = rt_r.replica.stats()
    assert st["staleness_ticks"] == 0, st
    assert st["applied_tick"] > (mid_applied or 0), \
        "applied tick must advance while tailing the live primary"
    # monitoring surfaces (satellite: role/applied_tick/staleness) —
    # checked while QUIESCENT (feed complete, replica caught up):
    # querying the primary first would append fresh commit ticks to the
    # WAL and race the exact-equality assertions below
    base = f"http://127.0.0.1:{rt_r.http_server.port}"
    status = json.loads(urllib.request.urlopen(
        base + "/status", timeout=10).read())
    assert status["role"] == "replica"
    assert status["applied_tick"] == st["applied_tick"]
    assert status["staleness_ticks"] == 0
    assert status["replica"]["tailed_sources"] == ["vecs"]
    hz = json.loads(urllib.request.urlopen(
        base + "/healthz", timeout=10).read())
    assert hz["role"] == "replica" and "staleness_ticks" in hz
    metrics = urllib.request.urlopen(
        base + "/metrics", timeout=10).read().decode()
    rid = st["replica_id"]
    assert (f'pathway_tpu_replica_staleness_ticks{{replica="{rid}"}} 0'
            in metrics)
    assert f'pathway_tpu_replica_applied_tick{{replica="{rid}"}}' \
        in metrics
    # the two serving tiers agree on the same index state (queries to
    # the primary tick its commit clock, but never mutate the vectors)
    primary_answers = [_ask(ws_p.port, q) for q in _QVECS]
    replica_answers = [_ask(ws_r.port, q) for q in _QVECS]
    assert replica_answers == primary_answers
    _streaming.stop_all()
    th_p.join(timeout=30)
    th_r.join(timeout=30)
    assert not errs_p and not errs_r, (errs_p, errs_r)


# ---------------------------------------------------------------------------
# router policy units
# ---------------------------------------------------------------------------

def _fake_endpoint(router, rid, *, role="replica", staleness=0,
                   p50=None, inflight=0, host="127.0.0.1", port=1):
    a, b = socket.socketpair()
    ep = ReplicaEndpoint(rid, role, host, port, a)
    ep.staleness_ticks = staleness
    ep.inflight = inflight
    if p50 is not None:
        for _ in range(8):
            ep.observe(p50)
    router._endpoints[rid] = ep
    return ep, b


def test_router_choose_latency_and_staleness():
    router = QueryRouter(max_staleness_ticks=10)
    fast, _ = _fake_endpoint(router, "fast", p50=2.0)
    _slow, _ = _fake_endpoint(router, "slow", p50=50.0)
    assert router.choose().replica_id == "fast"
    # the fast one goes stale past the bound: the fresh one wins even
    # though it is slower
    fast.staleness_ticks = 99
    assert router.choose().replica_id == "slow"
    # ALL stale: availability wins — least-stale is served, never a 503
    router._endpoints["slow"].staleness_ticks = 200
    assert router.choose().replica_id == "fast"
    # inflight load shifts the latency-aware choice
    fast.staleness_ticks = 0
    router._endpoints["slow"].staleness_ticks = 0
    fast.inflight = 100
    assert router.choose().replica_id == "slow"


def test_router_reexplores_idle_endpoint():
    """An endpoint whose latency estimate was seeded during cold start
    (huge p50) but that nobody routed to for reexplore_s scores 0 and is
    probed again — the estimate must not starve it forever."""
    router = QueryRouter()
    router.reexplore_s = 5.0
    _fast, _ = _fake_endpoint(router, "fast", p50=2.0)
    slow, _ = _fake_endpoint(router, "slow", p50=5000.0)
    assert router.choose().replica_id == "fast"
    # the slow one has been idle past the window: re-explored
    slow.last_routed_at = time.monotonic() - 10.0
    assert router.choose().replica_id == "slow"
    # choice stamped: the very next pick goes back to the fast one, not
    # a second blind probe of the re-explored endpoint
    assert router.choose().replica_id == "fast"


def test_router_choose_prefers_replicas_over_primary():
    router = QueryRouter()
    _p, _ = _fake_endpoint(router, "primary-1", role="primary", p50=1.0)
    _r, _ = _fake_endpoint(router, "replica-1", p50=30.0)
    assert router.choose().replica_id == "replica-1"
    # the replica dies: the read-serving primary is the last resort
    router._endpoints["replica-1"].alive = False
    assert router.choose().replica_id == "primary-1"
    router._endpoints["primary-1"].alive = False
    with pytest.raises(NoReplicaAvailable):
        router.choose()


def test_router_burn_rate_scaling_decisions():
    router = QueryRouter(slo_ms=10.0, error_budget=0.01)
    spawned = []
    retired = []
    router._spawn_cb = lambda: spawned.append(1)
    router._retire_cb = retired.append
    router.scale_cooldown_s = 0.0
    router.min_replicas = 1
    router.max_replicas = 4
    _a, _ = _fake_endpoint(router, "a", p50=5.0)
    _b, peer_b = _fake_endpoint(router, "b", p50=80.0)
    # burning hot: every request violates the 10 ms SLO
    for _ in range(64):
        router._window.append(50.0)
    assert router.burn_rate() > 1.0
    assert router.maybe_scale() == "out"
    assert spawned == [1]
    # cold: scale in retires the worst-p95 replica with a stop frame
    router._window.clear()
    for _ in range(64):
        router._window.append(1.0)
    assert router.maybe_scale() == "in"
    assert retired == ["b"]
    tag, payload = recv_control_frame(peer_b)
    assert tag == "stop" and payload["reason"] == "scale-in"
    assert router._endpoints["b"].retiring
    # a retiring endpoint is never chosen
    assert router.choose().replica_id == "a"


def test_router_scale_cooldown_blocks_thrash():
    router = QueryRouter(slo_ms=10.0)
    router._spawn_cb = lambda: None
    router.scale_cooldown_s = 3600.0
    _a, _ = _fake_endpoint(router, "a")
    for _ in range(64):
        router._window.append(50.0)
    assert router.maybe_scale() == "out" or router.maybe_scale() is None
    assert router.maybe_scale() is None  # cooldown holds


# ---------------------------------------------------------------------------
# router end to end: control protocol + proxy + failover
# ---------------------------------------------------------------------------

class _FakeReplicaHTTP:
    """A minimal serving stand-in answering every POST with its name."""

    def __init__(self, name: str):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        outer = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                body = json.dumps({"served_by": outer.name}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.name = name
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        self._t = threading.Thread(target=self.httpd.serve_forever,
                                   daemon=True)
        self._t.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _register_replica(router, rid, port, *, role="replica",
                      staleness=0) -> socket.socket:
    """Speak the real control protocol: HMAC handshake, hello, one
    heartbeat."""
    sock = socket.create_connection(("127.0.0.1", router.control_port),
                                    timeout=5)
    hmac_handshake(sock, control_authkey(), time.monotonic() + 5)
    sock.settimeout(None)
    send_control_frame(sock, "hello", {"replica": rid, "role": role,
                                       "host": "127.0.0.1", "port": port})
    send_control_frame(sock, "hb", {"replica": rid, "applied_tick": 7,
                                    "primary_watermark": 7,
                                    "staleness_ticks": staleness,
                                    "generation": 1})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        eps = {e.replica_id: e for e in router.endpoints()}
        if rid in eps and eps[rid].applied_tick == 7:
            return sock
        time.sleep(0.02)
    raise TimeoutError(f"router never registered {rid}")


def test_router_end_to_end_proxy_failover_and_metrics():
    router = QueryRouter()
    router.start()
    serving = _FakeReplicaHTTP("alive-replica")
    try:
        # a dead endpoint registers first (cold -> chosen first): the
        # forward fails over and the query is NOT lost
        dead_sock = _register_replica(router, "dead-replica", 1)
        live_sock = _register_replica(router, "alive-replica",
                                      serving.port)
        body = json.dumps({"q": 1}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/q", data=body,
            method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["served_by"] == "alive-replica"
            assert resp.headers["X-Pathway-Replica"] == "alive-replica"
            assert int(resp.headers["X-Pathway-Failovers"]) >= 1
        assert router.failovers_total >= 1
        assert router.requests_total == 1
        # every further query lands on the live replica; zero lost
        for _ in range(5):
            with urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{router.port}/q", data=body,
                        method="POST"), timeout=30) as resp:
                assert resp.status == 200
        assert router.unroutable_total == 0
        # control-socket EOF removes the endpoint from the fleet
        dead_sock.close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and any(
                e.replica_id == "dead-replica"
                for e in router.endpoints()):
            time.sleep(0.02)
        assert all(e.replica_id != "dead-replica"
                   for e in router.endpoints())
        # local monitoring contract: role=router + per-replica families
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/healthz", timeout=10).read())
        assert hz["role"] == "router" and hz["replicas_live"] >= 1
        status = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/status", timeout=10).read())
        assert status["role"] == "router"
        assert any(r["replica"] == "alive-replica"
                   for r in status["replicas"])
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/metrics",
            timeout=10).read().decode()
        assert ('pathway_tpu_router_requests{replica="alive-replica"}'
                in metrics)
        assert ('pathway_tpu_replica_staleness_ticks'
                '{replica="alive-replica"} 0' in metrics)
        assert 'pathway_tpu_router_replica_p50_ms{replica=' in metrics
        live_sock.close()
    finally:
        serving.stop()
        router.stop()


def test_router_503_when_fleet_empty():
    router = QueryRouter()
    router.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/q", data=b"{}",
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
        assert router.unroutable_total == 1
        hz_req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/healthz")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(hz_req, timeout=10)
        assert ei.value.code == 503  # empty fleet = degraded router
    finally:
        router.stop()


def test_control_frame_roundtrip_rejects_bad_authkey():
    """The control listener refuses a peer with the wrong PATHWAY_RUN_ID
    authkey (the HMAC handshake fails) and stays up for genuine peers."""
    router = QueryRouter()
    router.start()
    serving = _FakeReplicaHTTP("ok")
    try:
        sock = socket.create_connection(
            ("127.0.0.1", router.control_port), timeout=5)
        try:
            hmac_handshake(sock, b"wrong-key", time.monotonic() + 3)
            # the listener may close before or after our check — either
            # way no endpoint must appear
        except Exception:
            pass
        finally:
            sock.close()
        time.sleep(0.2)
        assert router.endpoints() == []
        # a genuine peer still registers afterwards
        ok = _register_replica(router, "ok", serving.port)
        assert [e.replica_id for e in router.endpoints()] == ["ok"]
        ok.close()
    finally:
        serving.stop()
        router.stop()
