"""Profiling canary: the continuous profiling plane's three load-bearing
promises, proven end to end (same pattern as trace_canary.py).

1. **Flamegraph gate** — drive ``examples/streaming_etl.py``'s real graph
   with ``PATHWAY_PROFILER=1``: the host sampler must produce non-empty
   collapsed-flamegraph text whose lines parse (``role;frame;... count``),
   with at least one sample attributed to an in-flight DEVICE leg (the
   ``[device:...]`` synthetic leaf the flight recorder tags), and the
   sampler's own rolling overhead accounting must stay under the 2%
   contract.

2. **Roofline gate** — a tiny-config run dispatches every kernel family
   the cost model knows (knn_search, ingest_scatter, encoder_forward,
   segment_attention); each dispatched family must carry a roofline
   classification (arithmetic intensity vs machine balance → compute- or
   bandwidth-bound) with sane numbers.

3. **Overhead guard** — per-tick wall time with the profiler SAMPLING
   must stay within 2% of profiler-off on the same join + sliding window
   + groupby shape trace_canary measures, min-of-K interleaved, with the
   retry-3 rule (a wall-clock ratio on a shared runner can blip on
   correlated noise; a real regression fails every attempt).

The gate numbers are written as a CI artifact (``PROFILING_BENCH_ARTIFACT``)
and checkpointed into ``BENCH_LASTGOOD.json`` per the evidence rule.

Exits 0 iff all hold. Run: ``python tests/profiling_canary.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

_RESULT: dict = {}

_COLLAPSED_LINE = re.compile(r"^[^; ][^;]*(;[^;]+)* \d+$")


def check_flamegraph() -> str | None:
    """Run the streaming example with the profiler forced on; return an
    error string or None."""
    from tests.pipelining_canary import _write_feed

    os.environ["PATHWAY_DEVICE_INFLIGHT"] = "2"
    os.environ["PATHWAY_PROFILER"] = "1"
    # production default interval: the 2% self-overhead contract is
    # stated (and measured) at this cadence
    os.environ.pop("PATHWAY_PROFILER_SAMPLE_MS", None)
    os.environ["PATHWAY_FLIGHT_RECORDER"] = "1"  # in-flight op tagging
    import pathway_tpu as pw
    from examples.streaming_etl import build
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.engine.profiler import current_profiler
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        orders_dir, cats_csv = _write_feed(root)
        out_csv = str(root / "out.csv")
        build(orders_dir, cats_csv, out_csv)
        import threading

        th = threading.Thread(target=pw.run, daemon=True)
        th.start()
        deadline = time.monotonic() + 60.0
        prof = None
        while time.monotonic() < deadline and prof is None:
            prof = current_profiler()
            time.sleep(0.05)
        if prof is None:
            _streaming.stop_all()
            th.join(15.0)
            return "profiler never installed (PATHWAY_PROFILER=1 ignored)"
        # run until the sampler caught a device leg in flight (the first
        # device-leg XLA compile alone is hundreds of sampler intervals)
        while time.monotonic() < deadline:
            if prof.device_attributed_samples >= 1 \
                    and prof.samples_total >= 50:
                break
            time.sleep(0.1)
        text = prof.collapsed()
        samples = prof.samples_total
        device_samples = prof.device_attributed_samples
        overhead = prof.overhead_ratio()
        stats = prof.stats()
        _streaming.stop_all()
        th.join(15.0)
        G.clear()
    os.environ.pop("PATHWAY_PROFILER", None)
    os.environ.pop("PATHWAY_FLIGHT_RECORDER", None)
    lines = text.strip().splitlines() if text.strip() else []
    if not lines:
        return "flamegraph is empty: the sampler collected nothing"
    for ln in lines:
        if not _COLLAPSED_LINE.match(ln):
            return f"malformed collapsed-stack line: {ln!r}"
    if device_samples < 1:
        return (f"no device-leg-attributed sample after {samples} samples "
                f"— in-flight tagging is broken")
    if not any("[device:" in ln for ln in lines):
        return "device-attributed samples counted but no [device:...] leaf"
    if overhead >= 0.02:
        return f"sampler self-overhead {overhead:.4f} >= the 2% contract"
    roles = {ln.split(";", 1)[0] for ln in lines}
    _RESULT.update({
        "profiling_flamegraph_stacks": len(lines),
        "profiling_samples_total": samples,
        "profiling_device_attributed_samples": device_samples,
        "profiling_sampler_overhead_ratio": round(overhead, 6),
        "profiling_thread_roles": sorted(roles),
        "profiling_mfu_rolling": stats["mfu_rolling"],
    })
    print(f"flamegraph gate OK: {len(lines)} folded stacks over "
          f"{samples} samples, {device_samples} device-attributed, "
          f"sampler overhead {overhead:.4%}, roles {sorted(roles)}")
    return None


def check_rooflines() -> str | None:
    """Dispatch every kernel family at tiny shapes; each must come back
    roofline-classified."""
    import numpy as np

    import jax.numpy as jnp
    from pathway_tpu.engine.profiler import (KERNEL_FAMILIES, Profiler,
                                             install_profiler)
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    prof = Profiler(sample_interval_ms=1e6)  # device side only
    install_profiler(prof)
    try:
        # knn_search + ingest_scatter
        rng = np.random.default_rng(11)
        vecs = rng.normal(size=(64, 16)).astype(np.float32)
        idx = BruteForceKnnIndex(16, metric=KnnMetric.L2SQ, paged=False)
        idx.add_batch([Pointer(i) for i in range(64)], vecs)
        idx.search([(Pointer(900), vecs[3], 4, None)])
        # encoder_forward (packed) + segment_attention (ragged)
        cfg = EncoderConfig.tiny(compute_dtype=jnp.float32)
        texts = ["tiny text", "a longer piece of text for packing",
                 "mid", "several words here"] * 3
        JaxEncoderEmbedder(config=cfg, ragged=False,
                           max_len=32).encode_batch_device(texts)
        JaxEncoderEmbedder(config=cfg, ragged=True,
                           max_len=32).encode_batch_device(texts)
        fams = prof.family_stats()
    finally:
        install_profiler(None)
    missing = [f for f in KERNEL_FAMILIES if f not in fams]
    if missing:
        return f"families never dispatched: {missing}"
    rooflines = {}
    for fam in KERNEL_FAMILIES:
        st = fams[fam]
        if st["dispatches"] < 1:
            return f"{fam}: zero dispatches recorded"
        rf = st["roofline"]
        if rf["bound_by"] not in ("compute", "bandwidth"):
            return f"{fam}: bad roofline verdict {rf['bound_by']!r}"
        if rf["arithmetic_intensity"] <= 0.0:
            return f"{fam}: non-positive arithmetic intensity"
        if not 0.0 < rf["attainable_mfu"] <= 1.0:
            return f"{fam}: attainable MFU {rf['attainable_mfu']} out of range"
        if st["device_ms_total"] <= 0.0:
            return f"{fam}: no device time recorded"
        rooflines[fam] = rf["bound_by"]
    # the slab scan and the scatter are bandwidth all the way down on
    # any real machine balance — a "compute" verdict here means the
    # bytes model lost its slab term
    if rooflines["knn_search"] != "bandwidth":
        return f"knn_search classified {rooflines['knn_search']}-bound"
    if rooflines["ingest_scatter"] != "bandwidth":
        return f"ingest_scatter classified {rooflines['ingest_scatter']}-bound"
    _RESULT["profiling_rooflines"] = rooflines
    _RESULT["profiling_family_dispatches"] = {
        f: fams[f]["dispatches"] for f in KERNEL_FAMILIES}
    print(f"roofline gate OK: {rooflines}")
    return None


def check_overhead(attempts: int = 3) -> str | None:
    """Profiler SAMPLING must add < 2% per-tick wall time vs off.

    Retry-3 rule: the gate passes on the first attempt under budget and
    only reports failure after ``attempts`` independent measurements all
    exceed it (correlated wall-clock noise on a shared runner)."""
    last = None
    for i in range(attempts):
        last = _measure_overhead()
        if last is None:
            return None
        print(f"overhead attempt {i + 1}/{attempts} over budget: {last}")
    return last


def _measure_overhead() -> str | None:
    from tests.trace_canary import _etl_like_graph

    from pathway_tpu.engine.profiler import Profiler, install_profiler
    from pathway_tpu.internals.parse_graph import G

    os.environ["PATHWAY_DEVICE_INFLIGHT"] = "1"  # no bridge-thread noise
    os.environ.pop("PATHWAY_PROFILER", None)
    n_rows, n_ticks, trials = 4000, 120, 5

    def run_once(with_profiler: bool) -> float:
        runner = _etl_like_graph(n_rows, n_ticks)
        prof = None
        if with_profiler:
            prof = Profiler()  # default 25ms sampling, like production
            install_profiler(prof)
            prof.start()
        t0 = time.perf_counter()
        try:
            runner.run_batch(n_workers=1)
        finally:
            if prof is not None:
                prof.stop()
                install_profiler(None)
        dt = time.perf_counter() - t0
        G.clear()
        return dt

    run_once(False)  # warm caches/imports off the record
    run_once(True)
    # interleaved trials: thermal / allocator drift must hit both modes
    # equally, or the guard measures the machine, not the sampler
    base_ts, prof_ts = [], []
    for _ in range(trials):
        base_ts.append(run_once(False))
        prof_ts.append(run_once(True))
    base, profiled = min(base_ts), min(prof_ts)
    ratio = profiled / base
    print(f"overhead guard: baseline {base * 1e3:.1f}ms, "
          f"profiler-sampling {profiled * 1e3:.1f}ms over {n_ticks} ticks "
          f"(ratio {ratio:.4f})")
    _RESULT["profiling_overhead_ratio_wall"] = round(ratio, 4)
    if ratio > 1.02:
        return (f"profiler-on per-tick overhead {ratio:.4f}x exceeds "
                f"the 2% budget")
    return None


def _write_artifacts() -> None:
    import bench

    bench._write_lastgood(_RESULT)  # evidence rule: checkpoint immediately
    artifact = os.environ.get("PROFILING_BENCH_ARTIFACT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump(_RESULT, f, indent=1)


def main() -> int:
    for name, check in (("flamegraph", check_flamegraph),
                        ("roofline", check_rooflines),
                        ("overhead", check_overhead)):
        err = check()
        if err:
            print(f"FAIL [{name}]: {err}", file=sys.stderr)
            return 1
    _write_artifacts()
    print("OK: flamegraph + roofline + overhead gates all hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
