"""Flight recorder: per-operator tick tracing, Chrome-trace export, and
stall attribution (engine/flight_recorder.py; reference: the OTLP span +
latency-gauge surface of src/engine/telemetry.rs:196-366).

Proves the acceptance contract:
- a run with PATHWAY_TRACE_PATH produces a Perfetto-loadable trace with
  host and device tracks and user-frame attribution on operator spans;
- the recorder is OFF by default (scheduler carries None — the one-branch
  hot path) and PATHWAY_FLIGHT_RECORDER=0 force-disables everything;
- a seeded device-leg hang is named — operator, leg, user frame — in the
  watchdog's post-mortem dump.
"""

from __future__ import annotations

import json
import logging
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.flight_recorder import FlightRecorder, attach_note
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _fresh():
    G.clear()
    yield
    G.clear()


class _FakeOp:
    pass


class _FakeNode:
    def __init__(self, id, name, trace=None):
        self.id = id
        self.name = name
        self.op = _FakeOp()
        self.trace = trace


# ---------------------------------------------------------------------------
# gating: off by default, env overrides
# ---------------------------------------------------------------------------

def test_recorder_off_by_default(monkeypatch):
    monkeypatch.delenv("PATHWAY_TRACE_PATH", raising=False)
    monkeypatch.delenv("PATHWAY_FLIGHT_RECORDER", raising=False)
    assert FlightRecorder.from_env() is None
    from pathway_tpu.internals.runner import GraphRunner

    t = pw.debug.table_from_markdown("""
    a
    1
    """)
    runner = GraphRunner()
    runner.capture(t.select(b=t.a + 1))
    runner.run_batch()
    assert runner._scheduler.recorder is None


def test_from_env_gating(monkeypatch, tmp_path):
    monkeypatch.setenv("PATHWAY_TRACE_PATH", str(tmp_path / "t.json"))
    rec = FlightRecorder.from_env()
    assert rec is not None and rec.enabled
    assert rec.trace_path == str(tmp_path / "t.json")
    # force-off beats everything
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "0")
    assert FlightRecorder.from_env() is None
    assert FlightRecorder.from_env(auto_on=True) is None
    # observable surfaces turn it on without a trace path
    monkeypatch.delenv("PATHWAY_FLIGHT_RECORDER")
    monkeypatch.delenv("PATHWAY_TRACE_PATH")
    assert FlightRecorder.from_env() is None
    rec = FlightRecorder.from_env(auto_on=True)
    assert rec is not None and rec.enabled and rec.trace_path is None


# ---------------------------------------------------------------------------
# ring buffer, histograms, dump
# ---------------------------------------------------------------------------

def test_tail_events_keeps_last_n_ticks():
    rec = FlightRecorder(buffer_events=1000)
    rec.enabled = True
    node = _FakeNode(0, "op")
    for tick in range(10):
        for _ in range(3):
            rec.record(tick, node, "host", 0.0, 1.0, 1, 1)
    tail = rec.tail_events(2)
    assert sorted({ev[0] for ev in tail}) == [8, 9]
    assert len(tail) == 6
    assert len(rec.tail_events(None)) == 30


def test_dump_tail_names_inflight_operator_and_frame():
    from pathway_tpu.internals.trace import Trace

    rec = FlightRecorder()
    rec.enabled = True
    trace = Trace("pipeline.py", 42, "build", "t.select(score=udf(...))")
    stuck = _FakeNode(7, "map:score", trace=trace)
    rec.record(1, _FakeNode(0, "source"), "host", 0.0, 0.5, 4, 4)
    rec.mark_op(2, stuck, "device")  # stepping… and never returning
    dump = rec.dump_tail()
    assert "tick 1 [host] source" in dump
    assert "IN FLIGHT" in dump and "map:score" in dump
    assert "[device]" in dump
    assert 'File "pipeline.py", line 42' in dump
    info = rec.inflight_summary()
    assert info["operator"] == "map:score" and info["leg"] == "device"
    # other threads churning through their own steps (host legs, sharded
    # pool replicas) must NOT evict the older stuck marker: slots are
    # keyed per stepping thread, and the hung thread never clears its own
    def churn():
        rec.mark_op(3, _FakeNode(1, "hostop"), "host")
        rec.clear_op()

    th = threading.Thread(target=churn)
    th.start()
    th.join()
    assert rec.inflight_summary()["operator"] == "map:score"


def test_attach_note_pre_311_storage():
    e = ValueError("x")
    attach_note(e, "note one")
    attach_note(e, "note one")  # idempotent
    attach_note(e, "note two")
    assert list(getattr(e, "__notes__", [])) == ["note one", "note two"]


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

def _check_nesting(events):
    """B/E pairs per tid must balance and nest like a call stack."""
    stacks: dict = {}
    for ev in events:
        if ev["ph"] == "B":
            stacks.setdefault(ev["tid"], []).append(ev["name"])
        elif ev["ph"] == "E":
            stack = stacks.setdefault(ev["tid"], [])
            assert stack, f"E without B on tid {ev['tid']}: {ev}"
            top = stack.pop()
            assert top == ev["name"], \
                f"mis-nested span: E {ev['name']!r} closes B {top!r}"
    for tid, stack in stacks.items():
        assert not stack, f"unclosed spans on tid {tid}: {stack}"


def test_batch_trace_file_is_valid_and_nested(monkeypatch, tmp_path):
    path = tmp_path / "trace.json"
    monkeypatch.setenv("PATHWAY_TRACE_PATH", str(path))
    t = pw.debug.table_from_markdown("""
    a | b
    1 | 2
    3 | 4
    """)
    out = t.select(c=t.a + t.b)
    pw.debug.compute_and_print(out)
    data = json.loads(path.read_text())
    events = data["traceEvents"]
    thread_names = {e["args"]["name"] for e in events
                    if e["ph"] == "M" and e["name"] == "thread_name"}
    assert thread_names == {"host leg", "device leg"}
    # fleet identity (PR 14): the process track is named role:process and
    # the payload carries the mergeable clock-anchor meta block
    proc_names = {e["args"]["name"] for e in events
                  if e["ph"] == "M" and e["name"] == "process_name"}
    assert len(proc_names) == 1 and next(iter(proc_names)).count(":") >= 1
    meta = data["pathway_meta"]
    assert meta["role"] and meta["process"]
    assert meta["epoch_wall_us"] > 0
    _check_nesting(events)
    b_ops = [e for e in events if e["ph"] == "B"
             and not e["name"].startswith("tick ")]
    assert b_ops, "no operator spans recorded"
    # operator spans carry user-frame attribution pointing at THIS file
    framed = [e for e in b_ops if "user_frame" in e.get("args", {})]
    assert any("test_flight_recorder.py" in e["args"]["user_frame"]
               for e in framed)
    # rows ride along
    assert all({"rows_in", "rows_out"} <= set(e["args"]) for e in b_ops)


def test_streaming_trace_has_device_track(monkeypatch, tmp_path):
    """A pipelined streaming run writes device-leg spans on their own
    track, with leg-level queue-wait/exec metadata on the tick wrapper."""
    import numpy as np

    path = tmp_path / "trace.json"
    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", "2")

    @pw.udf(batch=True, device=True, deterministic=True, return_type=int)
    def dev_len(ws):
        import jax.numpy as jnp

        arr = jnp.asarray(np.asarray([len(w) for w in ws], np.int32))
        return [int(v) for v in np.asarray(arr)]

    class Subj(pw.io.python.ConnectorSubject):
        def run(self):
            for w in ["aa", "bbb", "c"]:
                self.next(word=w)

    t = pw.io.python.read(Subj(), schema=pw.schema_from_types(word=str),
                          autocommit_duration_ms=10)
    t = t.select(word=t.word, wl=dev_len(t.word))
    pw.io.subscribe(t, lambda *a, **k: None)
    pw.run(trace_path=str(path))
    data = json.loads(path.read_text())
    events = data["traceEvents"]
    _check_nesting(events)
    host_b = [e for e in events if e["ph"] == "B" and e.get("cat") == "host"]
    dev_b = [e for e in events if e["ph"] == "B" and e.get("cat") == "device"]
    assert host_b and dev_b, "expected spans on both tracks"
    assert {e["tid"] for e in host_b} != {e["tid"] for e in dev_b}
    wrappers = [e for e in dev_b if e["name"].startswith("tick ")
                and "queue_wait_ms" in e["args"]]
    assert wrappers, "device tick wrappers carry no queue-wait attribution"
    assert any(e["name"].startswith("map:") for e in dev_b)


# ---------------------------------------------------------------------------
# seeded device-leg hang → post-mortem names the stuck operator
# ---------------------------------------------------------------------------

def test_seeded_device_leg_hang_named_in_postmortem(monkeypatch, caplog):
    """The BENCH_r05 scenario, reproduced and attributed: a device leg
    that hangs stalls the commit loop (backpressure), the watchdog fires,
    and its post-mortem dump names the stuck operator with its user frame
    — instead of 'tunnel unhealthy' naming nothing."""
    import numpy as np

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", "2")
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "1")
    release = threading.Event()

    @pw.udf(batch=True, device=True, deterministic=True, return_type=int)
    def stuck_score(ws):
        release.wait(20.0)  # the seeded hang: blocks until the test says go
        return [len(w) for w in np.asarray(ws, dtype=object)]

    class Subj(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(word="hello")

    t = pw.io.python.read(Subj(), schema=pw.schema_from_types(word=str),
                          autocommit_duration_ms=10)
    t = t.select(word=t.word, s=stuck_score(t.word))
    pw.io.subscribe(t, lambda *a, **k: None)

    fired = threading.Event()

    class _Spy(logging.Handler):
        messages: list = []

        def emit(self, record):
            msg = record.getMessage()
            type(self).messages.append(msg)
            if "commit loop has not ticked" in msg:
                fired.set()
                release.set()  # unblock so the run can finish cleanly

    spy = _Spy()
    _Spy.messages = []
    sup_logger = logging.getLogger("pathway_tpu.engine.supervisor")
    sup_logger.addHandler(spy)
    try:
        pw.run(watchdog=pw.WatchdogConfig(tick_deadline_s=0.4,
                                          poll_interval_s=0.05))
    finally:
        sup_logger.removeHandler(spy)
    assert fired.wait(0.1), "watchdog never reported the stalled commit loop"
    stall = next(m for m in _Spy.messages
                 if "commit loop has not ticked" in m)
    assert "flight recorder tail" in stall
    assert "IN FLIGHT" in stall
    assert "[device]" in stall and "map:" in stall
    assert "test_flight_recorder.py" in stall  # the user frame


# ---------------------------------------------------------------------------
# OTel span flow (API-level fake SDK: no exporter packages needed)
# ---------------------------------------------------------------------------

def test_recorded_spans_flow_through_telemetry_provider():
    spans = []

    class _Span:
        def __init__(self, name, start):
            self.name = name
            self.start = start
            self.attrs = {}
            self.end_ns = None

        def set_attribute(self, k, v):
            self.attrs[k] = v

        def end(self, end_time=None):
            self.end_ns = end_time

    class _Tracer:
        def start_span(self, name, start_time=None):
            sp = _Span(name, start_time)
            spans.append(sp)
            return sp

    class _Telemetry:
        _provider = object()  # a "real SDK pipeline is wired" marker
        tracer = _Tracer()

    rec = FlightRecorder()
    rec.enabled = True
    rec.set_telemetry(_Telemetry())
    from pathway_tpu.internals.trace import Trace

    node = _FakeNode(3, "groupby:sales",
                     trace=Trace("app.py", 7, "main", "t.groupby(...)"))
    rec.record(5, node, "device", time.perf_counter(), 12.5, 100, 4)
    assert len(spans) == 1
    sp = spans[0]
    assert sp.name == "pathway.operator.groupby:sales"
    assert sp.attrs["pathway.tick"] == 5
    assert sp.attrs["pathway.leg"] == "device"
    assert sp.attrs["pathway.rows_in"] == 100
    assert "app.py" in sp.attrs["pathway.user_frame"]
    assert sp.end_ns is not None and sp.end_ns > sp.start
    # API-only mode (no SDK provider) must NOT pay span construction
    class _ApiOnly:
        _provider = None
        tracer = _Tracer()

    rec2 = FlightRecorder()
    rec2.enabled = True
    rec2.set_telemetry(_ApiOnly())
    rec2.record(1, node, "host", 0.0, 1.0, 1, 1)
    assert len(spans) == 1
