"""Pipelined device runtime (engine/device_bridge.py + scheduler legs).

Contracts under test:

- pipelined (PATHWAY_DEVICE_INFLIGHT >= 2) and synchronous execution
  produce byte-identical captured streams, for both the device-UDF path
  and the external-KNN-index path;
- backpressure bounds the number of in-flight ticks at the window, for
  any window size (property-style sweep);
- a device leg in flight does not trip the watchdog, and exceptions on
  the bridge worker re-raise (original type) on the host thread;
- crash → restart → replay stays exactly-once with a device leg in the
  pipeline (persistence commits sit behind the resolve barrier);
- satellites: bounded scheduler route cache, zero-copy embedder rows,
  pw.warmup / compilation cache wiring.
"""

from __future__ import annotations

import time as _time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.delta import Delta, row_fingerprint
from pathway_tpu.engine.device_bridge import DeviceBridge
from pathway_tpu.engine.graph import CapturedStream, EngineGraph, Scheduler
from pathway_tpu.engine.operators import Operator, OutputOperator
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner
from pathway_tpu.internals.keys import Pointer


@pytest.fixture(autouse=True)
def _fresh():
    G.clear()
    yield
    G.clear()


@pw.udf(batch=True, device=True, deterministic=True, return_type=float)
def _dev_square(xs):
    import jax.numpy as jnp

    return [float(v) for v in
            np.asarray(jnp.square(jnp.asarray(np.asarray(xs, np.float32))))]


def _run_udf_pipeline(monkeypatch, inflight: int):
    from pathway_tpu.debug import table_from_rows

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", str(inflight))
    G.clear()
    schema = sch.schema_from_types(x=float)
    rows = [(float(i), i // 4, 1) for i in range(32)]
    # a same-stream retraction exercises the deferred leg's diff handling
    rows.append((5.0, 6, -1))
    rows.append((105.0, 6, 1))
    t = table_from_rows(schema, rows, is_stream=True)
    out = t.select(x=t.x, sq=_dev_square(t.x))
    runner = GraphRunner()
    cap = runner.capture(out)
    runner.run_batch(n_workers=1)
    stats = runner._scheduler.bridge_stats()
    G.clear()
    return cap.events, stats


def test_pipelined_udf_byte_identical_to_sync(monkeypatch):
    sync_events, sync_stats = _run_udf_pipeline(monkeypatch, 1)
    pipe_events, pipe_stats = _run_udf_pipeline(monkeypatch, 2)
    assert sync_stats is None  # inflight=1 never builds a bridge
    assert pipe_stats is not None and pipe_stats["legs_resolved"] > 0
    assert pipe_events == sync_events
    assert sync_events  # non-vacuous


def test_pipelined_knn_index_byte_identical_to_sync(monkeypatch):
    def run(inflight: int):
        from pathway_tpu.debug import table_from_rows
        from pathway_tpu.stdlib.indexing import (
            default_brute_force_knn_document_index,
        )

        monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", str(inflight))
        G.clear()
        rng = np.random.default_rng(7)
        data_schema = sch.schema_from_types(v=np.ndarray)
        vecs = [rng.random(8, dtype=np.float32) for _ in range(20)]
        data = table_from_rows(
            data_schema, [(v, i // 5, 1) for i, v in enumerate(vecs)],
            is_stream=True)
        q_schema = sch.schema_from_types(qv=np.ndarray, k=int)
        queries = table_from_rows(
            q_schema, [(vecs[3] + 0.01, 4, 2, 1), (vecs[11] + 0.01, 4, 3, 1)],
            is_stream=True)
        index = default_brute_force_knn_document_index(
            data.v, data, dimensions=8)
        res = index.query_as_of_now(queries.qv, number_of_matches=queries.k)
        runner = GraphRunner()
        cap = runner.capture(res)
        runner.run_batch(n_workers=1)
        stats = runner._scheduler.bridge_stats()
        G.clear()
        return cap.events, stats

    sync_events, sync_stats = run(1)
    pipe_events, pipe_stats = run(2)
    assert sync_stats is None
    assert pipe_stats is not None and pipe_stats["legs_resolved"] > 0
    canon = lambda evs: [(k, row_fingerprint(r), t, d)  # noqa: E731
                         for k, r, t, d in evs]
    assert canon(pipe_events) == canon(sync_events)
    assert sync_events


# ---------------------------------------------------------------------------
# backpressure bounds in-flight ticks (property-style over window sizes)
# ---------------------------------------------------------------------------

class _SlowDeviceOp(Operator):
    device_bound = True

    def __init__(self, sleep_s: float):
        self.sleep_s = sleep_s

    def step(self, time, in_deltas):
        _time.sleep(self.sleep_s)
        return in_deltas[0]


def _drive_slow_graph(inflight: int, n_ticks: int, sleep_s: float = 0.01,
                      host_sleep_s: float = 0.0):
    g = EngineGraph()
    src = g.add_source("src")
    dev = g.add_node(_SlowDeviceOp(sleep_s), [src], "dev")
    cap = CapturedStream()
    g.add_node(OutputOperator(cap.on_delta), [dev], "capture")
    sched = Scheduler(g, n_workers=1, device_inflight=inflight)
    depths = []
    for t in range(1, n_ticks + 1):
        sched.push_source(src, Delta([(Pointer(t), (t,), 1)]))
        sched.run_time(t)
        if sched._bridge is not None:
            depths.append(sched._bridge.depth())
        if host_sleep_s:
            _time.sleep(host_sleep_s)  # simulated host-side work
    sched.resolve_barrier()
    stats = sched.bridge_stats()
    sched.close()
    return cap.events, stats, depths


@pytest.mark.parametrize("inflight", [2, 3, 5])
def test_backpressure_bounds_inflight_ticks(inflight):
    events, stats, depths = _drive_slow_graph(inflight, n_ticks=12)
    assert stats["legs_dispatched"] == 12
    assert stats["legs_resolved"] == 12
    # the property: at no point were more than `inflight` ticks in flight
    assert stats["max_depth"] <= inflight
    assert max(depths) <= inflight
    # and the window was actually used (the device is slower than the host)
    assert stats["max_depth"] >= 2
    # byte-identical to the synchronous run
    sync_events, sync_stats, _ = _drive_slow_graph(1, n_ticks=12)
    assert sync_stats is None
    assert events == sync_events


def test_bridge_overlap_is_observable():
    # a balanced pipeline (host work ≈ device work): most legs resolve
    # while the host thread is busy with a later tick, and the bridge's
    # counters make that visible. (With an idle host the bridge correctly
    # reports ~0 overlap: blocking in backpressure is not overlap.)
    _events, stats, _depths = _drive_slow_graph(
        2, n_ticks=10, sleep_s=0.01, host_sleep_s=0.015)
    assert stats["legs_overlapped"] > 0
    assert stats["overlap_ratio"] > 0


# ---------------------------------------------------------------------------
# failure propagation + barrier
# ---------------------------------------------------------------------------

class _BoomError(RuntimeError):
    pass


class _FailingDeviceOp(Operator):
    device_bound = True

    def __init__(self, fail_at_tick: int):
        self.fail_at_tick = fail_at_tick

    def step(self, time, in_deltas):
        if time == self.fail_at_tick:
            raise _BoomError(f"device fault at tick {time}")
        return in_deltas[0]


def test_device_leg_error_reraises_on_host_thread():
    g = EngineGraph()
    src = g.add_source("src")
    g.add_node(_FailingDeviceOp(fail_at_tick=2), [src], "dev")
    sched = Scheduler(g, n_workers=1, device_inflight=2)
    try:
        with pytest.raises(_BoomError):
            for t in range(1, 8):
                sched.push_source(src, Delta([(Pointer(t), (t,), 1)]))
                sched.run_time(t)
            sched.resolve_barrier()  # error surfaces here at the latest
    finally:
        sched.close()


def test_device_leg_error_surfaces_after_external_stop(monkeypatch):
    """A leg that fails right before an external stop must still escape
    pw.run(): teardown drains the bridge without raising, so the runtime
    re-raises the stored error after cleanup (review fix: the stop path
    previously returned success with the tick's outputs missing)."""
    import threading

    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.testing.faults import hanging_subject

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", "2")
    G.clear()
    release = threading.Event()
    subject = hanging_subject([{"x": 1.0}])  # one row, then hang

    t = pw.io.python.read(subject, schema=sch.schema_from_types(x=float),
                          autocommit_duration_ms=10)
    t = t.select(x=t.x, y=_dev_square(t.x))

    def exploding_sink(*a, **k):
        release.wait(10)  # hold the leg until the loop is stopped
        raise _BoomError("sink failure on the device leg")

    pw.io.subscribe(t, exploding_sink)
    box: dict = {}

    def run():
        try:
            pw.run()
        except BaseException as e:  # noqa: BLE001
            box["error"] = e

    th = threading.Thread(target=run, daemon=True)
    th.start()
    deadline = _time.monotonic() + 10.0
    rt = None
    while _time.monotonic() < deadline:
        live = list(_streaming._ACTIVE_RUNTIMES)
        if live and live[0].scheduler._bridge is not None \
                and live[0].scheduler._bridge.depth() > 0:
            rt = live[0]
            break
        _time.sleep(0.005)
    assert rt is not None, "device leg never started"
    rt.stop()  # external stop while the leg is still in flight
    release.set()
    th.join(15.0)
    assert not th.is_alive()
    assert isinstance(box.get("error"), _BoomError)


def test_take_device_error_after_drain_without_raise():
    """The exact swallow window the streaming fix closes: a leg fails,
    nothing submits or barriers afterwards, close() drains silently —
    take_device_error() must still hand the failure back for re-raise."""
    g = EngineGraph()
    src = g.add_source("src")
    g.add_node(_FailingDeviceOp(fail_at_tick=1), [src], "dev")
    sched = Scheduler(g, n_workers=1, device_inflight=2)
    sched.push_source(src, Delta([(Pointer(1), (1,), 1)]))
    sched.run_time(1)  # leg fails on the worker; nothing observes it
    sched.close()  # drain-without-raise (the teardown path)
    err = sched.take_device_error()
    assert isinstance(err, _BoomError)


def test_outputs_view_resolves_on_access():
    g = EngineGraph()
    src = g.add_source("src")
    dev = g.add_node(_SlowDeviceOp(0.05), [src], "dev")
    sched = Scheduler(g, n_workers=1, device_inflight=2)
    try:
        sched.push_source(src, Delta([(Pointer(1), (1,), 1)]))
        outputs = sched.run_time(1)
        # reading a deferred node's delta is a hard resolve barrier
        delta = outputs.get(dev.id)
        assert [e[:2] for e in delta.entries] == [(Pointer(1), (1,))]
        assert sched.bridge_stats()["legs_resolved"] == 1
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# streaming: watchdog with a leg in flight; exactly-once under crash/replay
# ---------------------------------------------------------------------------

def test_watchdog_tick_with_device_leg_in_flight(monkeypatch):
    """A slow (but healthy) device leg must not trip the watchdog: the
    commit loop keeps ticking while legs resolve behind it."""
    from pathway_tpu.testing.faults import flaky_subject

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", "2")
    G.clear()
    subject = flaky_subject([{"x": float(i)} for i in range(12)],
                            fail_after=0, fail_attempts=0, delay_s=0.01)

    @pw.udf(batch=True, device=True, deterministic=True, return_type=float)
    def slow_dev(xs):
        import jax.numpy as jnp

        _time.sleep(0.05)  # leg outlives several 10 ms commit ticks
        return [float(v) for v in
                np.asarray(jnp.asarray(np.asarray(xs, np.float32)) * 2.0)]

    t = pw.io.python.read(subject, schema=sch.schema_from_types(x=float),
                          autocommit_duration_ms=10)
    out = t.select(x=t.x, y=slow_dev(t.x))
    state = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["x"]] = row["y"]

    pw.io.subscribe(out, on_change)
    pw.run(watchdog=pw.WatchdogConfig(tick_deadline_s=20.0,
                                      poll_interval_s=0.05))
    assert state == {float(i): float(i) * 2.0 for i in range(12)}


@pytest.mark.parametrize("autojit", ["0", "1"])
def test_crash_replay_exactly_once_with_device_leg(monkeypatch, autojit):
    """The fault-tolerance contract with a device leg in the pipeline:
    a crash mid-stream, a backoff restart and a fresh-process replay all
    produce the baseline's exact state (persistence checkpoints sit
    behind the resolve barrier). Parametrized over PATHWAY_AUTO_JIT: with
    the tier ON the traceable scoring UDF fuses and its map joins the
    device leg (internals/autojit.py), so the crash points also cover an
    auto-jitted dispatch in flight."""
    from pathway_tpu.internals import autojit as autojit_mod
    from pathway_tpu.internals.retries import FixedDelayRetryStrategy
    from pathway_tpu.testing.faults import flaky_subject

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", "2")
    monkeypatch.setenv("PATHWAY_AUTO_JIT", autojit)
    # ticks are tiny here: drop the dispatch floor so the fused program
    # actually executes under the crash points
    monkeypatch.setattr(autojit_mod, "MIN_ROWS", 1)
    autojit_mod.reset_stats()
    words = ["a", "b", "a", "c", "b", "a"]

    @pw.udf(batch=True, device=True, deterministic=True, return_type=int)
    def dev_len(ws):
        import jax.numpy as jnp

        arr = jnp.asarray(np.asarray([len(w) for w in ws], np.int32))
        return [int(v) for v in np.asarray(arr + 1)]

    @pw.udf
    def score(wl: int) -> int:
        return wl * 5 + 1

    def run_counts(subject, backend=None, policy=None):
        G.clear()
        t = pw.io.python.read(
            subject, schema=sch.schema_from_types(word=str),
            autocommit_duration_ms=10, persistent_id="devwords",
            connector_policy=policy)
        t = t.select(word=t.word, wl=dev_len(t.word))
        t = t.select(word=t.word, wl=score(t.wl))
        counts = t.groupby(t.word).reduce(
            word=t.word, c=pw.reducers.count(), wl=pw.reducers.max(t.wl))
        state = {}

        def on_change(key, row, time, is_addition):
            if is_addition:
                state[row["word"]] = (row["c"], row["wl"])
            elif state.get(row["word"]) == (row["c"], row["wl"]):
                del state[row["word"]]

        pw.io.subscribe(counts, on_change)
        cfg = None
        if backend is not None:
            cfg = pw.persistence.Config.simple_config(backend)
        pw.run(persistence_config=cfg)
        return state

    rows = [{"word": w} for w in words]
    baseline = run_counts(flaky_subject(rows, fail_after=0, fail_attempts=0))
    assert baseline == {"a": (3, 11), "b": (2, 11), "c": (1, 11)}

    backend = pw.persistence.Backend.mock()
    policy = pw.ConnectorPolicy(
        max_retries=3, retry_strategy=FixedDelayRetryStrategy(delay_ms=20))
    subject = flaky_subject(rows, fail_after=3, fail_attempts=2)
    state = run_counts(subject, backend=backend, policy=policy)
    assert state == baseline
    # the durable log replays to the same state on a fresh process-run
    replay = run_counts(flaky_subject(rows, fail_after=0, fail_attempts=0),
                        backend=backend)
    assert replay == baseline
    if autojit == "1":
        # non-vacuous: the fused program really dispatched under the
        # crash/restart/replay sequence
        stats = autojit_mod.autojit_stats()
        assert stats["programs"] >= 1
        assert (stats["device_dispatches"] + stats["vector_dispatches"]) > 0
        assert stats["demotions"] == 0


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_route_cache_cap_parses_env(monkeypatch):
    monkeypatch.setenv("PATHWAY_ROUTE_CACHE_MAX", "2048")
    g = EngineGraph()
    g.add_source("src")
    sched = Scheduler(g, n_workers=2, device_inflight=1)
    try:
        assert sched._route_cache_max == 2048
    finally:
        sched.close()
    monkeypatch.setenv("PATHWAY_ROUTE_CACHE_MAX", "not-a-number")
    sched = Scheduler(g, n_workers=2, device_inflight=1)
    try:
        assert sched._route_cache_max == 1 << 16  # tolerant fallback
    finally:
        sched.close()


def test_route_cache_cap_applied_in_sharded_run(monkeypatch):
    """End-to-end: a high-cardinality instance column routed across
    workers never grows any edge memo past the cap."""
    from pathway_tpu.debug import table_from_rows

    monkeypatch.setenv("PATHWAY_ROUTE_CACHE_MAX", "1024")
    G.clear()
    schema = sch.schema_from_types(k=str, x=int)
    rows = [(f"user-{i}", i, 0, 1) for i in range(1500)]
    t = table_from_rows(schema, rows, is_stream=True)
    counts = t.groupby(t.k).reduce(k=t.k, n=pw.reducers.count())
    runner = GraphRunner()
    runner.capture(counts)
    runner.run_batch(n_workers=2)
    sched = runner._scheduler
    assert all(len(c) <= sched._route_cache_max
               for c in sched._route_cache.values())
    G.clear()


def test_embedder_rows_are_zero_copy_views():
    from pathway_tpu.models.encoder import EncoderConfig, init_params
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder
    import jax

    cfg = EncoderConfig(vocab_size=64, hidden=16, layers=1, heads=2,
                        intermediate=32, max_len=32)
    emb = JaxEncoderEmbedder(
        config=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        max_len=32)
    rows = emb.__wrapped__(["hello world", "second doc", "third"])
    assert len(rows) == 3
    # one host transfer, zero-copy row views into it
    assert all(r.base is not None for r in rows)
    assert all(r.base is rows[0].base for r in rows)
    assert np.shares_memory(rows[0], rows[0].base)


def test_bucket_widths_cover_every_bucket():
    from pathway_tpu.models.encoder import EncoderConfig, init_params
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder
    import jax

    cfg = EncoderConfig(vocab_size=64, hidden=16, layers=1, heads=2,
                        intermediate=32, max_len=512)
    emb = JaxEncoderEmbedder(
        config=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        max_len=512)
    widths = emb.bucket_widths()
    assert len(widths) == 18  # the "~18 shapes" from the bucketing design
    # every bucket the padder can produce is in the warm set
    assert {emb._bucket(n) for n in range(1, 513)} == set(widths)


def test_warmup_compiles_bucket_shapes(tmp_path, monkeypatch):
    from pathway_tpu.models.encoder import EncoderConfig, init_params
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder
    import jax

    monkeypatch.setenv("PATHWAY_COMPILATION_CACHE", str(tmp_path / "xla"))
    cfg = EncoderConfig(vocab_size=64, hidden=16, layers=1, heads=2,
                        intermediate=32, max_len=48)
    emb = JaxEncoderEmbedder(
        config=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        max_len=48, max_batch_size=4)
    report = pw.warmup(emb)
    # autojit entries belong to fused programs other tests may have left
    # gc-pending in the weak registry — the encoder ladder is ours
    ladder = [e for e in report["compiled"] if e[0] != "autojit"]
    kinds = [k for k, _shape in ladder]
    assert kinds == ["encode"] * len(emb.bucket_widths())
    shapes = [s for _k, s in ladder]
    assert shapes == [(4, w) for w in emb.bucket_widths()]
    # warmed shapes serve without further compilation (smoke: runs fast)
    out = emb.embed_batch(["a b c", "d"])
    assert out.shape == (2, 16)


def test_warmup_fused_index_leaves_index_empty():
    from pathway_tpu.models.encoder import EncoderConfig, init_params
    from pathway_tpu.ops.knn import BruteForceKnnIndex, DeviceEmbeddingKnnIndex
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder
    import jax

    cfg = EncoderConfig(vocab_size=64, hidden=16, layers=1, heads=2,
                        intermediate=32, max_len=32)
    emb = JaxEncoderEmbedder(
        config=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        max_len=32, max_batch_size=4)
    index = DeviceEmbeddingKnnIndex(
        emb, BruteForceKnnIndex(16, reserved_space=64))
    report = pw.warmup(emb, index=index, cache=False)
    assert [k for k, _ in report["compiled"] if k != "autojit"] \
        == ["fused_ingest"] * len(emb.bucket_widths())
    assert len(index) == 0  # scratch slots retracted
    # the warmed index still ingests + answers correctly
    index.add_batch([Pointer(1), Pointer(2)], ["hello world", "other doc"])
    (reply,) = index.search([(Pointer(9), "hello world", 1, None)])
    assert reply[0][0] == Pointer(1)


def test_warmup_full_slab_falls_back_and_flushes(monkeypatch):
    """Slab too full for scratch slots mid-sweep: earlier widths' scratch
    removals must still flush (no plain-scatter compile in the first live
    tick) and the remaining widths warm the plain encoder — the dispatch
    the live two-dispatch fallback actually uses."""
    from pathway_tpu.models.encoder import EncoderConfig, init_params
    from pathway_tpu.ops.knn import BruteForceKnnIndex, DeviceEmbeddingKnnIndex
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder
    import jax

    cfg = EncoderConfig(vocab_size=64, hidden=16, layers=1, heads=2,
                        intermediate=32, max_len=32)
    emb = JaxEncoderEmbedder(
        config=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
        max_len=32, max_batch_size=4)
    index = DeviceEmbeddingKnnIndex(
        emb, BruteForceKnnIndex(16, reserved_space=64))
    widths = emb.bucket_widths()
    real_fused = index._fused
    calls = {"n": 0}

    def fused_then_full(keys, params, ids, lens):
        calls["n"] += 1
        if calls["n"] > 1:  # second width onward: pretend the slab is full
            raise ValueError("fused ingest cannot grow the slab (donated "
                             "shape is pinned) — reserve capacity up front")
        return real_fused(keys, params, ids, lens)

    index._fused = fused_then_full
    report = pw.warmup(emb, index=index, cache=False)
    kinds = [k for k, _ in report["compiled"] if k != "autojit"]
    assert kinds == ["fused_ingest"] + ["encode"] * (len(widths) - 1)
    # the width-1 scratch removals were flushed (dirty set drained), so
    # the first live ingest pays no plain-scatter compile for them
    assert not index.inner._dirty
    assert len(index) == 0


def test_enable_compilation_cache_sets_jax_config(tmp_path):
    import jax

    path = pw.enable_compilation_cache(str(tmp_path / "cache"))
    if path is None:  # ancient jax without persistent-cache support
        pytest.skip("jax lacks persistent compilation cache")
    assert (tmp_path / "cache").is_dir()
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "cache")


def test_device_bridge_standalone_fifo_order():
    bridge = DeviceBridge(max_inflight=2)
    order = []
    for t in range(5):
        bridge.submit(t, lambda t=t: order.append(t))
    bridge.barrier()
    bridge.close()
    assert order == list(range(5))
    stats = bridge.stats()
    assert stats["legs_resolved"] == 5
    assert stats["depth"] == 0
