"""Core Table ops (reference test analogue: python/pathway/tests/test_common.py)."""

import pytest

import pathway_tpu as pw
from tests.utils import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
    rows_of,
)


def test_select_arithmetic():
    t = T("""
    a | b
    1 | 2
    3 | 4
    """)
    r = t.select(c=t.a + t.b, d=t.a * t.b, e=t.b / t.a, f=t.b % t.a)
    assert rows_of(r) == [(3, 2, 2.0, 0), (7, 12, 4 / 3, 1)]


def test_select_this():
    t = T("""
    a | b
    1 | 2
    """)
    r = t.select(pw.this.a, c=pw.this.b + 1)
    assert rows_of(r) == [(1, 3)]


def test_with_columns():
    t = T("""
    a | b
    1 | 2
    """)
    r = t.with_columns(c=t.a + t.b)
    assert rows_of(r) == [(1, 2, 3)]


def test_filter_keeps_keys():
    t = T("""
    a
    1
    2
    3
    """)
    r = t.filter(t.a >= 2)
    expected = T("""
    a
    2
    3
    """)
    assert_table_equality_wo_index(r, expected)


def test_rename_without():
    t = T("""
    a | b | c
    1 | 2 | 3
    """)
    assert rows_of(t.without("b")) == [(1, 3)]
    r = t.rename_by_dict({"a": "x"})
    assert r.column_names() == ["x", "b", "c"]


def test_cast_and_types():
    t = T("""
    a
    1
    2
    """)
    r = t.select(b=pw.cast(float, t.a))
    assert rows_of(r) == [(1.0,), (2.0,)]


def test_concat_reindex_and_update_rows():
    t1 = T("""
    a
    1
    """)
    t2 = T("""
    a
    2
    """)
    c = t1.concat_reindex(t2)
    assert sorted(rows_of(c)) == [(1,), (2,)]

    u = T("""
    id | a
    1  | 10
    2  | 20
    """)
    v = T("""
    id | a
    2  | 99
    3  | 30
    """)
    merged = u.update_rows(v)
    assert sorted(rows_of(merged)) == [(10,), (30,), (99,)]


def test_update_cells():
    u = T("""
    id | a | b
    1  | 1 | x
    2  | 2 | y
    """)
    v = T("""
    id | b
    2  | z
    """)
    r = u.update_cells(v)
    assert sorted(rows_of(r)) == [(1, "x"), (2, "z")]


def test_difference_intersect():
    t1 = T("""
    id | a
    1  | 1
    2  | 2
    3  | 3
    """)
    t2 = T("""
    id | b
    2  | 0
    3  | 0
    """)
    assert rows_of(t1.difference(t2)) == [(1,)]
    assert sorted(rows_of(t1.intersect(t2))) == [(2,), (3,)]


def test_with_id_from():
    t = T("""
    a | b
    1 | x
    2 | y
    """)
    r = t.with_id_from(t.a)
    r2 = t.with_id_from(t.a)
    assert_table_equality(r, r2)


def test_ix():
    orders = T("""
    id | item_id | qty
    1  | 10      | 2
    2  | 20      | 3
    """)
    items = T("""
    iid | name
    10  | apple
    20  | pear
    """)
    # build pointer column on orders matching items' reindexed ids
    orders2 = orders.select(ptr=orders.pointer_from(orders.item_id), qty=orders.qty)
    items2 = items.with_id_from(items.iid)
    fetched = items2.ix(orders2.ptr, context=orders2)
    r = orders2.select(orders2.qty, name=fetched.name)
    assert sorted(rows_of(r)) == [(2, "apple"), (3, "pear")]


def test_flatten():
    t = T("""
    s
    'a b'
    'c'
    """)
    r = t.select(w=t.s.str.split(" ")).flatten(pw.this.w)
    assert sorted(rows_of(r)) == [("a",), ("b",), ("c",)]


def test_sort_prev_next():
    t = T("""
    a
    3
    1
    2
    """)
    s = t.sort(t.a)
    both_none = s.filter(s.prev.is_none() & s.next.is_none())
    assert rows_of(both_none) == []
    firsts = s.filter(s.prev.is_none())
    r = t.restrict(firsts).select(t.a)
    assert rows_of(r) == [(1,)]


def test_deduplicate():
    t = T("""
    a | _time
    1 | 2
    2 | 4
    5 | 6
    3 | 8
    """)
    r = t.deduplicate(value=t.a, acceptor=lambda new, old: new > old)
    assert rows_of(r) == [(5,)]


def test_groupby_id():
    t = T("""
    a
    1
    2
    """)
    r = t.groupby(id=t.id).reduce(s=pw.reducers.sum(t.a))
    assert_table_equality_wo_index(r, t.select(s=t.a))


def test_split():
    t = T("""
    a
    1
    2
    3
    """)
    pos, neg = t.split(t.a > 1)
    assert sorted(rows_of(pos)) == [(2,), (3,)]
    assert rows_of(neg) == [(1,)]


def test_typed_equality_catches_dtype_drift():
    """assert_table_equality compares column dtypes: an int column that
    drifted to float must FAIL typed equality while still passing the
    _wo_types variant (reference: typed vs _wo_types assert split)."""
    import pytest

    from tests.utils import (assert_table_equality,
                             assert_table_equality_wo_index,
                             assert_table_equality_wo_index_types)

    ints = T("""
    a
    1
    2
    """)
    floats = ints.select(a=pw.cast(float, pw.this.a))
    with pytest.raises(AssertionError, match="dtypes"):
        assert_table_equality(floats, ints)
    with pytest.raises(AssertionError, match="dtypes"):
        assert_table_equality_wo_index(floats, ints)
    # same values modulo type: the permissive variant accepts int 1 vs 1.0
    assert_table_equality_wo_index_types(ints, ints)
