"""The native engine passes (native/fastjoin.cpp, native/fastgroup.cpp)
must be byte-equivalent to their pure-Python fallbacks — same events, same
keys, same order-insensitive stream — on a pipeline that exercises
groupby churn, join upsert fusion, retractions, None join keys and
mixed-type keys."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine import operators as ops
from pathway_tpu.engine.delta import row_fingerprint
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner


def _pipeline_events(n_workers: int):
    G.clear()
    rows = []
    for i in range(300):
        rows.append((f"k{i % 17}", i % 5, 2 * (i % 7), 1))
        if i % 11 == 0 and i > 0:
            rows.append(rows[i - 2][:2] + (2 * (i % 7) + 2, -1))
    t = pw.debug.table_from_rows(
        pw.schema_from_types(word=str, qty=int), rows, is_stream=True)
    lex = pw.debug.table_from_rows(
        pw.schema_from_types(word=str, cat=str),
        [(f"k{j}", f"c{j % 3}") for j in range(17)])
    g = t.groupby(t.word).reduce(
        t.word, n=pw.reducers.count(), s=pw.reducers.sum(t.qty),
        m=pw.reducers.avg(t.qty))
    j = g.join(lex, g.word == lex.word).select(g.word, g.n, g.s, lex.cat)
    runner = GraphRunner()
    cap = runner.capture(j)
    runner.run_batch(n_workers=n_workers)
    out = sorted((k, row_fingerprint(r), tm, d)
                 for k, r, tm, d in cap.consolidated_events())
    G.clear()
    return out


@pytest.mark.parametrize("n_workers", [1, 4])
def test_native_and_python_paths_identical(n_workers, monkeypatch):
    """Event-for-event parity, INCLUDING output keys — which pins the
    native u128 mix against internals/keys.py mix_pointers."""
    assert ops._get_fastjoin() is not None, "native join pass failed to build"
    assert ops._get_fastgroup() is not None, \
        "native groupby pass failed to build"
    native = _pipeline_events(n_workers)
    monkeypatch.setattr(ops, "_FASTJOIN", None)
    monkeypatch.setattr(ops, "_FASTGROUP", None)
    python = _pipeline_events(n_workers)
    assert native == python
    assert any(d for *_x, d in native)  # produced real events


def test_str_subclass_join_keys_match_plain_str_on_both_paths(monkeypatch):
    """np.str_ keys must join against plain str identically with and
    without the native pass (exact-type raw checks + canonicalization)."""
    import numpy as np

    def run():
        G.clear()
        left = pw.debug.table_from_rows(
            pw.schema_from_types(k=str, x=int), [(np.str_("a"), 1)])
        right = pw.debug.table_from_rows(
            pw.schema_from_types(k=str, y=int), [("a", 10)])
        j = left.join(right, left.k == right.k).select(left.x, right.y)
        runner = GraphRunner()
        cap = runner.capture(j)
        runner.run_batch()
        out = sorted(cap.snapshot().values())
        G.clear()
        return out

    native = run()
    assert native == [(1, 10)]
    monkeypatch.setattr(ops, "_FASTJOIN", None)
    monkeypatch.setattr(ops, "_FASTGROUP", None)
    assert run() == native
