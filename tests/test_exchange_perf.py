"""Pinned exchange-plane serialization performance.

Round 5 regressed exchange encode/decode 1.45 → 6.5 µs/row (4.5×) and the
only witness was a bench artifact nobody gated on. This test pins the
relationship that regression broke: the PACKED payload format
(engine/multiproc.py _pack_payload — columnar key/value arrays instead of
per-row tuples) must stay cheaper than naively pickling the same rows,
in both bytes and best-case encode+decode time.

Timing in CI is noisy, so the time assertion takes the BEST of several
trials (a regression of the r5 class is a 4.5× systematic slowdown — it
survives min-of-N; scheduler jitter does not) and the threshold leaves
~2× headroom over the measured ratio (~0.3-0.8 on an idle core).
"""

from __future__ import annotations

import pickle
import time

import pytest

from pathway_tpu.engine.multiproc import _pack_payload, _unpack_payload
from pathway_tpu.internals.keys import hash_values

N_ROWS = 20_000
TRIALS = 5
# packed must never cost more than 1.5x a plain pickle of the same rows
# (the r5 regression put it at ~4.5x) …
MAX_TIME_RATIO = 1.5
# … and must stay byte-smaller on the wire
MAX_BYTES_RATIO = 1.0


def _payload():
    ents = [(hash_values("row", i), (f"w{i % 5000}", int(i % 9 + 1)), 1)
            for i in range(N_ROWS)]
    return {"rows": {0: {0: ents}}, "wm": None, "bcast": None}


def _encdec_seconds(enc, dec):
    t0 = time.perf_counter()
    blob = enc()
    mid = time.perf_counter()
    dec(blob)
    return mid - t0, time.perf_counter() - mid, blob


def test_packed_exchange_beats_pickle():
    payload = _payload()
    best_ratio = float("inf")
    bytes_ratio = None
    for _ in range(TRIALS):
        p_enc, p_dec, p_blob = _encdec_seconds(
            lambda: pickle.dumps(("x", _pack_payload(payload)),
                                 protocol=pickle.HIGHEST_PROTOCOL),
            lambda b: _unpack_payload(pickle.loads(b)[1]))
        n_enc, n_dec, n_blob = _encdec_seconds(
            lambda: pickle.dumps(("x", payload),
                                 protocol=pickle.HIGHEST_PROTOCOL),
            pickle.loads)
        best_ratio = min(best_ratio,
                         (p_enc + p_dec) / max(n_enc + n_dec, 1e-9))
        bytes_ratio = len(p_blob) / len(n_blob)
    assert bytes_ratio <= MAX_BYTES_RATIO, (
        f"packed payload grew past plain pickle on the wire: "
        f"{bytes_ratio:.2f}x")
    assert best_ratio <= MAX_TIME_RATIO, (
        f"packed encode+decode is {best_ratio:.2f}x plain pickle "
        f"(> {MAX_TIME_RATIO}x): the exchange plane regressed — see "
        f"ROADMAP 'Rebuild the exchange plane' and the r5 1.45→6.5 "
        f"µs/row incident")


def test_packed_roundtrip_is_lossless():
    payload = _payload()
    out = _unpack_payload(pickle.loads(pickle.dumps(
        ("x", _pack_payload(payload)),
        protocol=pickle.HIGHEST_PROTOCOL))[1])
    assert out == payload


@pytest.mark.parametrize("rows", [0, 1])
def test_packed_tiny_payloads(rows):
    ents = [(hash_values("row", i), ("w", 1), 1) for i in range(rows)]
    payload = {"rows": {0: {0: ents}}, "wm": 7, "bcast": None}
    assert _unpack_payload(_pack_payload(payload)) == payload
