"""Pinned exchange-plane serialization performance.

Round 5 regressed exchange encode/decode 1.45 → 6.5 µs/row (4.5×) and the
only witness was a bench artifact nobody gated on. Diagnosis (this PR):
the bench timed a SINGLE encode+decode trial, and decode allocates tens of
thousands of objects per call — whenever a generational GC pass (gen-2 is
proportional to the whole live heap, huge after earlier bench legs) landed
inside the one timed window, the number exploded. Two pins prevent a
recurrence:

1. **Relative**: the columnar wire format (engine/wire.py) must stay
   cheaper than naively pickling the same payload, in both bytes and
   best-case encode+decode time (the original PR-7 gate, now over the
   columnar codec).
2. **Absolute** (new): best-of-5 encode+decode on the columnar path must
   stay ≤ 3.0 µs/row on the r05 payload shape — the regression class is
   caught in absolute terms, not just relative ones.

Timing in CI is noisy, so both assertions take the BEST of several trials
(a regression of the r5 class is a systematic slowdown — it survives
min-of-N; scheduler jitter and stray GC passes do not).
"""

from __future__ import annotations

import pickle
import time

import pytest

from pathway_tpu.engine import wire
from pathway_tpu.internals.keys import hash_values

N_ROWS = 20_000
TRIALS = 5
# columnar must never cost more than 1.5x a plain pickle of the same rows
# (the r5 regression put the old packed format at ~4.5x) …
MAX_TIME_RATIO = 1.5
# … must stay byte-smaller on the wire …
MAX_BYTES_RATIO = 1.0
# … and must stay under an absolute per-row budget (measured ~1.0-1.9
# µs/row best-of-5 on a 2-core container; 6.495 at the r05 incident)
MAX_ABS_US_PER_ROW = 3.0


def _payload():
    ents = [(hash_values("row", i), (f"w{i % 5000}", int(i % 9 + 1)), 1)
            for i in range(N_ROWS)]
    return {"rows": {0: {0: ents}}, "wm": None, "bcast": None}


def _encdec_seconds(enc, dec):
    t0 = time.perf_counter()
    blob = enc()
    mid = time.perf_counter()
    dec(blob)
    return mid - t0, time.perf_counter() - mid, blob


def _wire_trial(payload):
    return _encdec_seconds(
        lambda: b"".join(wire.encode_frame(("x", 1, 0), payload)[0]),
        wire.decode_frame)


def test_columnar_exchange_beats_pickle():
    payload = _payload()
    best_ratio = float("inf")
    bytes_ratio = None
    for _ in range(TRIALS):
        c_enc, c_dec, c_blob = _wire_trial(payload)
        n_enc, n_dec, n_blob = _encdec_seconds(
            lambda: pickle.dumps(("x", payload),
                                 protocol=pickle.HIGHEST_PROTOCOL),
            pickle.loads)
        best_ratio = min(best_ratio,
                         (c_enc + c_dec) / max(n_enc + n_dec, 1e-9))
        bytes_ratio = len(c_blob) / len(n_blob)
    assert bytes_ratio <= MAX_BYTES_RATIO, (
        f"columnar payload grew past plain pickle on the wire: "
        f"{bytes_ratio:.2f}x")
    assert best_ratio <= MAX_TIME_RATIO, (
        f"columnar encode+decode is {best_ratio:.2f}x plain pickle "
        f"(> {MAX_TIME_RATIO}x): the exchange plane regressed — see "
        f"ROADMAP 'Rebuild the exchange plane' and the r5 1.45→6.5 "
        f"µs/row incident")


def test_columnar_exchange_absolute_budget():
    """The r05 class in absolute terms: best-of-5 enc+dec on the columnar
    path ≤ 3.0 µs/row. A ratio gate alone would pass if pickle got slower
    alongside us; this one cannot.

    GC stays ON (the codec's own allocation pressure is genuine cost),
    but the long-lived session heap is frozen for the measurement:
    a gen-2 pass scanning pytest's whole import graph inside a trial is
    exactly the environment noise the r05 diagnosis named, not a codec
    property — without the freeze this gate flakes at ~3.5 µs/row on a
    busy 2-core box."""
    import gc

    payload = _payload()
    best_us = float("inf")
    gc.collect()
    gc.freeze()
    try:
        for _ in range(TRIALS):
            enc_s, dec_s, _blob = _wire_trial(payload)
            best_us = min(best_us, (enc_s + dec_s) / N_ROWS * 1e6)
    finally:
        gc.unfreeze()
    assert best_us <= MAX_ABS_US_PER_ROW, (
        f"columnar encode+decode best-of-{TRIALS} is {best_us:.3f} µs/row "
        f"(> {MAX_ABS_US_PER_ROW}): the exchange plane regressed in "
        f"absolute terms (r05 was 6.495)")


def test_columnar_frame_is_columnar():
    """The gate must measure the fast path: the r05 payload shape has to
    take the columnar frame kind, not the pickle fallback."""
    chunks, total, n_rows = wire.encode_frame(("x", 1, 0), _payload())
    blob = b"".join(chunks)
    assert blob[:2] == wire.MAGIC
    assert blob[3] == wire.KIND_COLUMNAR
    assert n_rows == N_ROWS
    assert total == len(blob)


def test_columnar_roundtrip_is_lossless():
    payload = _payload()
    chunks, _total, _rows = wire.encode_frame(("x", 1, 0), payload)
    tag, out, _ = wire.decode_frame(b"".join(chunks))
    assert tag == ("x", 1, 0)
    assert out == payload


@pytest.mark.parametrize("rows", [0, 1])
def test_columnar_tiny_payloads(rows):
    ents = [(hash_values("row", i), ("w", 1), 1) for i in range(rows)]
    payload = {"rows": {0: {0: ents}}, "wm": 7, "bcast": None}
    chunks, _total, n = wire.encode_frame(("x", 0, 0), payload)
    _tag, out, n2 = wire.decode_frame(b"".join(chunks))
    assert out == payload
    assert n == n2 == rows
