"""Steady-state device sanitizer (engine/device_sanitizer.py): the
env-armed lifecycle (off → armed → steady → suspended), the compile-miss
hook raising/recording on post-warmup compiles, the transfer guard
blocking implicit host→device operand transfers, the bench-facing
compile counter, and the warmup compile-count pins the PWT4xx family
gates at runtime — mirrors tests/test_lock_sanitizer.py for the
env-armed-instrument pattern."""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pathway_tpu.engine import device_sanitizer as ds  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    monkeypatch.delenv("PATHWAY_DEVICE_SANITIZER", raising=False)
    ds._reset_for_tests()
    yield
    ds._reset_for_tests()


def _fresh_jit(salt: float):
    """A jitted fn no other test has compiled (the salt lands in the
    executable, so jax's in-process cache can't serve it)."""
    return jax.jit(lambda x: x * 2.0 + salt)


# ---------------------------------------------------------------------------
# off by default — everything is a no-op
# ---------------------------------------------------------------------------

def test_disabled_sanitizer_is_inert():
    assert not ds.sanitizer_enabled()
    assert ds.arm() is False
    assert ds.declare_steady_state() is False
    assert not ds.in_steady_state()
    # dispatching fresh code is nobody's business when off
    f = _fresh_jit(0.125)
    f(jax.device_put(np.ones((4,), np.float32)))
    assert ds.violations() == []


@pytest.mark.parametrize("val,enabled,raises", [
    ("1", True, True), ("true", True, True), ("on", True, True),
    ("report", True, False), ("warn", True, False), ("", False, False),
    ("0", False, False)])
def test_env_contract(monkeypatch, val, enabled, raises):
    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", val)
    assert ds.sanitizer_enabled() is enabled
    if enabled:
        assert ds._raise_on_violation() is raises


# ---------------------------------------------------------------------------
# armed lifecycle
# ---------------------------------------------------------------------------

def test_warmup_window_counts_compiles_without_violating(monkeypatch):
    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "1")
    assert ds.arm() is True
    assert not ds.in_steady_state()
    f = _fresh_jit(0.25)
    f(jax.device_put(np.ones((4,), np.float32)))
    assert ds.warmup_compiles() > 0
    assert ds.post_warmup_compiles() == 0
    assert ds.violations() == []


def test_post_warmup_compile_raises_and_cached_dispatch_is_free(
        monkeypatch):
    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "1")
    ds.arm()
    f = _fresh_jit(0.375)
    x = jax.device_put(np.ones((4,), np.float32))
    f(x)  # warm
    ds.declare_steady_state()
    assert ds.in_steady_state()
    f(x)  # cache hit: silent
    assert ds.post_warmup_compiles() == 0
    g = _fresh_jit(0.4375)
    with pytest.raises(ds.DeviceDisciplineViolation,
                       match="steady-state serving window"):
        g(x)
    assert ds.post_warmup_compiles() == 1
    assert [v["kind"] for v in ds.violations()] == ["post-warmup-compile"]
    # the violation names the remediation path
    assert "suspend_steady_state" in ds.violations()[0]["message"]


def test_steady_state_blocks_implicit_transfer(monkeypatch):
    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "1")
    ds.arm()
    f = _fresh_jit(0.5)
    host = np.ones((4,), np.float32)
    f(jax.device_put(host))  # warm at this shape
    ds.declare_steady_state()
    # explicit residency establishment stays legal — that is the fix
    f(jax.device_put(host))
    with pytest.raises(Exception, match="[Tt]ransfer"):
        f(host)  # implicit numpy operand transfer


def test_suspend_steady_state_reopens_warmup_window(monkeypatch):
    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "1")
    ds.arm()
    x = jax.device_put(np.ones((4,), np.float32))
    _fresh_jit(0.625)(x)
    ds.declare_steady_state()
    before = ds.warmup_compiles()
    with ds.suspend_steady_state("slab growth"):
        assert not ds.in_steady_state()
        _fresh_jit(0.6875)(x)  # legal maintenance compile
        _fresh_jit(0.6875)(np.ones((4,), np.float32))  # transfers too
    assert ds.in_steady_state()  # restored on exit
    assert ds.warmup_compiles() > before
    assert ds.post_warmup_compiles() == 0
    assert ds.violations() == []


def test_report_mode_records_without_raising(monkeypatch):
    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "report")
    ds.arm()
    x = jax.device_put(np.ones((4,), np.float32))
    _fresh_jit(0.75)(x)
    ds.declare_steady_state()
    _fresh_jit(0.8125)(x)  # would raise in enforce mode
    assert ds.post_warmup_compiles() >= 1
    assert any(v["kind"] == "post-warmup-compile"
               for v in ds.violations())


def test_install_compile_counter_needs_no_env():
    count = ds.install_compile_counter()
    before = count()
    _fresh_jit(0.875)(jax.device_put(np.ones((4,), np.float32)))
    assert count() > before
    assert ds.violations() == []  # counter never enforces


# ---------------------------------------------------------------------------
# pw.warmup integration + compile-count pins
# ---------------------------------------------------------------------------

def _tiny_cfg(max_len=64):
    from pathway_tpu.models.encoder import EncoderConfig

    return EncoderConfig(vocab_size=64, hidden=16, layers=1, heads=2,
                         intermediate=32, max_len=max_len)


def test_warmup_declares_steady_state(monkeypatch):
    import pathway_tpu as pw

    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "1")
    pw.warmup(cache=False)  # no embedder: still brackets the window
    assert ds.in_steady_state()
    assert ds.post_warmup_compiles() == 0


def test_rewarmup_of_armed_process_is_not_a_violation(monkeypatch):
    import pathway_tpu as pw

    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "1")
    pw.warmup(cache=False)
    assert ds.in_steady_state()
    pw.warmup(cache=False)  # re-warm: suspends, never violates
    assert ds.in_steady_state()
    assert ds.violations() == []


@pytest.mark.slow
def test_ragged_encoder_ladder_pin_under_sanitizer(monkeypatch):
    """The ragged compile set stays ≤ 6 ladder entries, and re-dispatching
    a warmed bucket in steady state compiles NOTHING."""
    import pathway_tpu as pw
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "1")
    emb = JaxEncoderEmbedder(config=_tiny_cfg(), ragged=True, max_len=64)
    out = pw.warmup(emb, cache=False)
    assert ds.in_steady_state()
    ladder = [e for e in out["compiled"] if e[0] != "autojit"]
    assert 0 < len(ladder) <= 6, out["compiled"]
    assert ds.warmup_compiles() > 0
    # steady state: the exact warmed (bucket, width) dispatch is free
    bucket = emb.ragged_buckets()[0]
    ops, _n_docs = emb.ragged_warmup_operands(bucket)
    emb._encode_ragged(emb.params, *(jnp.asarray(a) for a in ops))
    assert ds.post_warmup_compiles() == 0
    assert ds.violations() == []


@pytest.mark.slow
def test_paged_multi_extent_search_zero_compiles_in_steady_state(
        monkeypatch):
    """After warmup walks the search fan-out over a MULTI-extent paged
    slab, a same-bucket query compiles nothing and transfers nothing
    implicitly — the steady-state serving contract, end to end."""
    import pathway_tpu as pw
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    monkeypatch.setenv("PATHWAY_DEVICE_SANITIZER", "1")
    idx = BruteForceKnnIndex(8, metric=KnnMetric.COS, paged=True,
                             page_rows=128)
    rng = np.random.default_rng(7)
    vecs = rng.normal(size=(300, 8)).astype(np.float32)  # 3 extents
    idx.add_batch([Pointer(i) for i in range(300)], vecs)
    idx.drain()
    pw.warmup(index=idx, ks=(3,), cache=False)
    assert ds.in_steady_state()
    res1 = idx.search([(Pointer(10 ** 6), vecs[5], 3, None)])
    assert res1[0][0][0] == Pointer(5)
    first = ds.post_warmup_compiles()
    # the second same-bucket query must be compile-free even if the
    # first touched a shape warmup missed
    res2 = idx.search([(Pointer(10 ** 6 + 1), vecs[9], 3, None)])
    assert res2[0][0][0] == Pointer(9)
    assert ds.post_warmup_compiles() == first == 0, ds.violations()
    assert ds.violations() == []
