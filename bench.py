"""Headline benchmark: RAG embed+index throughput + p50 KNN latency @10M.

Measures BOTH halves of the north-star metric from BASELINE.md:

1. documents → tokenize → flagship encoder forward (BGE-small shape,
   bfloat16, jit) → KNN index add (HBM slab scatter). Target: ≥50k
   docs/sec on v5e-8 ⇒ 6250 docs/sec/chip.
2. brute-force KNN query latency against a 10M x 384 bf16 slab resident
   in one chip's HBM (7.7 GB; the search is HBM-bandwidth-bound, chunked
   lax.scan kernel in ops/knn.py). Target: p50 < 20 ms.

Prints ONE JSON line; the KNN figures ride along as knn_* fields.
Override the slab size with BENCH_KNN_N (e.g. for CPU smoke runs).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_DOCS_PER_SEC_PER_CHIP = 50_000 / 8
KNN_TARGET_P50_MS = 20.0
KNN_N = int(os.environ.get("BENCH_KNN_N", 10_000_000))
KNN_DIM = 384
# docs/dispatch: amortizes per-execute overhead (the axon dev tunnel adds
# ~65 ms per dispatch). Measured 2026-07-29: 2048 ≥ 4096/8192 on this
# tunnel (larger batches pay proportionally more upload per dispatch)
BATCH = int(os.environ.get("BENCH_BATCH", 2048))
SKIP = set(os.environ.get("BENCH_SKIP", "").split(","))
# every leg that runs in the killable device-phase subprocesses
_DEVICE_LEG_NAMES = {"embed", "framework", "knn", "serving"}
SEQ = 128
WORDS_PER_DOC = 90


def make_docs(n: int, seed: int = 0) -> list[str]:
    rng = np.random.default_rng(seed)
    vocab = [f"word{i}" for i in range(4096)]
    idx = rng.integers(0, len(vocab), size=(n, WORDS_PER_DOC))
    return [" ".join(vocab[j] for j in row) for row in idx]


# Peak bf16 throughput used for the MFU estimate (v5e ≈ 197 TFLOP/s;
# override with BENCH_PEAK_TFLOPS for other chips). Resolved through the
# shared machine-parameter table (engine/profiler.py) so the bench and
# the live roofline gauges always describe the same chip.
def _peak_tflops() -> float:
    from pathway_tpu.engine.profiler import machine_params

    return machine_params()["peak_tflops"]


PEAK_TFLOPS = _peak_tflops()
# Wall-clock budget for the device-leg subprocess (embed + 10M-slab knn)
# per-group wall-clock budget, TOTAL across its retries (healthy runs:
# embed+framework ≈ 6 min, knn incl. int8 ≈ 15 min — well inside)
DEVICE_TIMEOUT_S = float(os.environ.get("BENCH_DEVICE_TIMEOUT", 1800.0))
DEVICE_TRIES = int(os.environ.get("BENCH_DEVICE_TRIES", 2))
# hard wall-clock budget for the WHOLE device phase (probe + all groups):
# without it the worst case was probe 17 min + 4 x 40 min group tries
# ≈ 3 h, and an outer driver timeout killing the bench mid-hang lost the
# round-5 rehearsal's entire output. 3000 s leaves the knn group ≥ 20 min
# even when the embed group burns its full budget on a half-wedged tunnel.
DEVICE_DEADLINE_S = float(os.environ.get("BENCH_DEVICE_DEADLINE", 3000.0))


def _encoder_flops_per_token(config, seq: int = SEQ) -> float:
    """Forward FLOPs/token for the encoder — resolved through the SHARED
    cost model (engine/profiler.py): the profiler's MFU gauges and the
    bench's MFU numbers are the same formula by construction, which
    tests/test_profiler.py pins (no drift between copies)."""
    from pathway_tpu.engine.profiler import encoder_flops_per_token

    return encoder_flops_per_token(config.hidden, config.intermediate,
                                   config.layers, seq)


_LEG_FNS = {
    "embed": lambda: bench_embed(),
    "framework": lambda: bench_embed_framework(),
    "knn": lambda: bench_knn(),
    "serving": lambda: bench_serving(),
}


class _DeviceEventCounter:
    """Per-leg XLA compile + implicit host→device transfer counts.

    Compiles come from the device sanitizer's monitoring listener
    (engine/device_sanitizer.install_compile_counter — a plain counter,
    no env gate). Transfers ride JAX's transfer guard in ``log`` mode,
    whose per-transfer lines come out of C++ (guard_lib.cc) on fd 2 —
    invisible to Python-level stderr hooks — so the guard window
    captures fd 2 into a temp file, counts the marker lines, and replays
    the bytes to the real stderr so nothing is swallowed. The counts
    join BENCH_HISTORY.jsonl as ``{leg}_compile_count`` /
    ``{leg}_transfer_count`` with lower-is-better pins in
    ``_BENCH_DIRECTIONS``: a recompile zoo or a new per-tick upload then
    fails ``--check-regression`` numerically even with the sanitizer
    off."""

    def __init__(self):
        from pathway_tpu.engine.device_sanitizer import \
            install_compile_counter

        self._compiles = install_compile_counter()

    def count(self, leg: str, fn):
        """Run ``fn()`` and return (its result, the events dict)."""
        import tempfile

        import jax

        c0 = self._compiles()
        tmp = tempfile.TemporaryFile()
        saved = os.dup(2)
        guarded = True
        try:
            # restore whatever mode was active (the device sanitizer may
            # hold "disallow" in steady state — don't weaken it for good)
            prev = jax.config.jax_transfer_guard_host_to_device or "allow"
            jax.config.update("jax_transfer_guard_host_to_device", "log")
        except Exception:  # noqa: BLE001 — older jax: compiles only
            guarded = False
        os.dup2(tmp.fileno(), 2)
        try:
            out = fn()
        finally:
            os.dup2(saved, 2)
            os.close(saved)
            if guarded:
                try:
                    jax.config.update(
                        "jax_transfer_guard_host_to_device", prev)
                except Exception:  # noqa: BLE001
                    pass
            tmp.seek(0)
            data = tmp.read()
            tmp.close()
            if data:
                try:
                    os.write(2, data)  # replay: keep stderr observable
                except OSError:
                    pass
        events = {f"{leg}_compile_count": self._compiles() - c0}
        if guarded:
            events[f"{leg}_transfer_count"] = sum(
                b"host-to-device transfer" in line
                for line in data.splitlines())
        return out, events

# serving-path SLO leg (bench_serving): slab size / dim / query count
SERVING_N = int(os.environ.get("BENCH_SERVING_N", 100_000))
SERVING_DIM = int(os.environ.get("BENCH_SERVING_DIM", KNN_DIM))
SERVING_QUERIES = int(os.environ.get("BENCH_SERVING_QUERIES", 48))
SERVING_WARMUP = int(os.environ.get("BENCH_SERVING_WARMUP", 8))

# QoS leg (bench_qos): same workload QoS-off vs QoS-on — the before/after
# artifact for "the controller actively trades ingest throughput for
# query latency" (engine/qos.py; ROADMAP "close the SLO control loop")
QOS_N = int(os.environ.get("BENCH_QOS_N", 20_000))
QOS_DIM = int(os.environ.get("BENCH_QOS_DIM", 64))
QOS_QUERIES = int(os.environ.get("BENCH_QOS_QUERIES", 32))
QOS_WARMUP = int(os.environ.get("BENCH_QOS_WARMUP", 6))
QOS_INGEST_CHUNK = int(os.environ.get("BENCH_QOS_INGEST_CHUNK", 1024))
QOS_INGEST_PERIOD_S = float(os.environ.get("BENCH_QOS_INGEST_PERIOD_S",
                                           0.05))
QOS_BURST = int(os.environ.get("BENCH_QOS_BURST", 32))
QOS_K = int(os.environ.get("BENCH_QOS_K", 10))
QOS_COMMIT_MS = int(os.environ.get("BENCH_QOS_COMMIT_MS", 5))

# Semantic result-cache leg (bench_semantic_cache): the SAME router-
# fronted serving fleet under a Zipf query stream with live ingest,
# cache-off vs cache-on (operator cache + router fleet cache). The
# Zipf head repeats, so the leg measures what the cache is FOR:
# identical (method, path, body) requests served at the router without
# touching a replica, and repeated query vectors served from the
# operator cache without a kernel dispatch.
SEM_POOL = int(os.environ.get("BENCH_SEM_POOL", 96))
SEM_ZIPF_S = float(os.environ.get("BENCH_SEM_ZIPF_S", 1.1))
SEM_SECONDS = float(os.environ.get("BENCH_SEM_SECONDS", 10.0))
SEM_WARMUP_S = float(os.environ.get("BENCH_SEM_WARMUP_S", 1.5))
SEM_CLIENTS = int(os.environ.get("BENCH_SEM_CLIENTS", 8))
SEM_COST_MS = float(os.environ.get("BENCH_SEM_COST_MS", 30.0))
SEM_VECS = int(os.environ.get("BENCH_SEM_VECS", 512))
# live-ingest cadence for BOTH phases: slow enough that the watermark
# holds across a forward (so router fills commit), fast enough that
# invalidations/tick stays a live number in the snapshot
SEM_TRICKLE_S = float(os.environ.get("BENCH_SEM_TRICKLE_S", 4.0))

# evidence rule (ROADMAP): the parent checkpoints every successful
# device-leg snapshot into BENCH_LASTGOOD.json the moment the child
# prints it, so a later hang / SIGKILL cannot erase captured numbers
_LASTGOOD_STATE: dict = {}


def _write_lastgood(snapshot: dict) -> None:
    path = os.environ.get("BENCH_LASTGOOD_PATH", "BENCH_LASTGOOD.json")
    try:
        from pathway_tpu.engine.flight_recorder import atomic_write_json

        if not _LASTGOOD_STATE and os.path.exists(path):
            # seed from the on-disk checkpoint so a single-leg run (the
            # CI jobs call one bench_* fn directly) REFINES the evidence
            # file instead of erasing every other leg's captured numbers
            try:
                with open(path) as f:
                    prior = json.load(f).get("result")
                if isinstance(prior, dict):
                    _LASTGOOD_STATE.update(prior)
            except Exception:  # noqa: BLE001 — a torn file must not block
                pass
        _LASTGOOD_STATE.update(
            {k: v for k, v in snapshot.items() if not k.endswith("error")})
        atomic_write_json(path, {"updated_at": time.time(),
                                 "result": dict(_LASTGOOD_STATE)})
    except Exception:  # noqa: BLE001 — evidence must never kill a leg
        pass


# -- perf-trajectory watch ----------------------------------------------------
# BENCH_LASTGOOD.json is a last-good SNAPSHOT; the trajectory lives in
# BENCH_HISTORY.jsonl (one row per leg metric per run: leg, metric, value,
# git sha, timestamp — engine/fleet_observability.py). Every leg appends
# its rows, and `bench.py --check-regression` compares each series'
# newest point against the trailing median of its prior points with
# per-metric tolerance bands — a CI-checkable time series instead of an
# empty trajectory (ROADMAP evidence rule).

def _append_bench_history(leg: str, metrics: dict) -> None:
    try:
        from pathway_tpu.engine.fleet_observability import \
            append_bench_history

        append_bench_history(leg, metrics)
    except Exception:  # noqa: BLE001 — evidence must never kill a leg
        pass
    _maybe_profile_epoch(leg)


# --profile: one cost-model + host-flamegraph snapshot per completed leg
# (engine/profiler.py profile_epoch), embedded as the "profile" key of
# the emitted BENCH_*.json line — the input `python -m pathway_tpu
# profdiff A.json B.json` compares when --check-regression flags a leg
_PROFILE_EPOCHS: list = []


def _maybe_profile_epoch(leg: str) -> None:
    try:
        from pathway_tpu.engine.profiler import current_profiler

        prof = current_profiler()
        if prof is not None and "--profile" in sys.argv:
            _PROFILE_EPOCHS.append({"leg": leg, **prof.profile_epoch()})
    except Exception:  # noqa: BLE001 — evidence must never kill a leg
        pass


# per-metric direction overrides for series the name heuristics cannot
# judge (engine/fleet_observability.metric_direction). The qos leg's
# series need them: "qos_shed_total" carries no marker at all (fewer
# sheds is better), and the ingest-rate pair is deliberately split —
# the OFF series is a plain throughput number (higher is better; a drop
# means the workload itself regressed) while the ON series is the
# CONTROLLER'S trade and moves with load, so it stays unwatched
# (reported, never gated) rather than coin-flipped.
_BENCH_DIRECTIONS = {
    "qos_shed_total": "lower",
    "qos_off_ingest_rate_rps": "higher",
    "qos_p50_speedup": "higher",
    # recovery leg: the bounded-restart contract is "smaller is better"
    # across the board. The ratio carries no unit marker at all (a bare
    # max/min quotient — growth means snapshot restart is no longer flat
    # in history size), and the restart series are pinned explicitly so
    # the suffix heuristic's `_s_<n>` match is a backstop, not the only
    # thing watching the recovery trajectory.
    "recovery_snapshot_ratio_maxmin": "lower",
    "recovery_walonly_restart_s_1000": "lower",
    "recovery_walonly_restart_s_10000": "lower",
    "recovery_walonly_restart_s_100000": "lower",
    "recovery_snapshot_restart_s_1000": "lower",
    "recovery_snapshot_restart_s_10000": "lower",
    "recovery_snapshot_restart_s_100000": "lower",
    # device-discipline columns (_DeviceEventCounter): bare counts carry
    # no unit marker the name heuristic could judge, and both are
    # strictly lower-is-better — a rising compile count is a recompile
    # zoo and a rising transfer count a new per-tick host→device upload,
    # caught numerically here even when PATHWAY_DEVICE_SANITIZER is off
    "embed_compile_count": "lower",
    "embed_transfer_count": "lower",
    "framework_compile_count": "lower",
    "framework_transfer_count": "lower",
    "knn_compile_count": "lower",
    "knn_transfer_count": "lower",
    "serving_compile_count": "lower",
    "serving_transfer_count": "lower",
    # failover leg (bench_replica): promotion wall-clock is the
    # write-unavailability window (smaller is better), and the fenced
    # zombie's write count is a bare counter — each one is a split-brain
    # write REFUSED; more of them means the zombie raced longer before
    # noticing its demotion
    "replica_failover_promotion_s": "lower",
    "replica_fenced_writes": "lower",
    # semantic result-cache leg: the speedup and both hit rates are the
    # headline (higher is better); router invalidations are watermark
    # moves observed by the cache — a climb means the fleet cache is
    # churning instead of serving. The `lost` counters are plain counts
    # with no unit marker: any rise is dropped queries.
    "semantic_cache_qps_speedup": "higher",
    "semantic_cache_router_hit_rate": "higher",
    "semantic_cache_op_hit_ratio": "higher",
    "semantic_cache_router_invalidations": "lower",
    "semantic_cache_off_lost": "lower",
    "semantic_cache_on_lost": "lower",
}


def check_regression_main(argv: list[str]) -> int:
    """``bench.py --check-regression``: gate the newest BENCH_HISTORY
    point of every watched series against its trailing median. Exit 0
    when the trajectory holds (or is too young to judge), 1 naming each
    regression otherwise. Knobs: ``--history PATH``
    (BENCH_HISTORY_PATH), ``--window N``, ``--min-prior N``,
    ``--tolerance F`` (BENCH_REGRESSION_TOLERANCE, default 0.35).
    Direction overrides for heuristic-blind series live in
    ``_BENCH_DIRECTIONS``."""
    from pathway_tpu.engine.fleet_observability import (
        bench_history_rows, check_regressions, history_path)

    opts = {"--history": None, "--window": "8", "--min-prior": "3",
            "--tolerance": None}
    profdiff_args: list[str] = []
    i = 0
    while i < len(argv):
        if argv[i] in opts and i + 1 < len(argv):
            opts[argv[i]] = argv[i + 1]
            i += 2
        elif argv[i] == "--profdiff" and i + 2 < len(argv):
            # name the dominant frame/kernel delta between a baseline
            # --profile artifact and the flagged run's (profdiff below
            # runs only when a regression actually fires)
            profdiff_args = [argv[i + 1], argv[i + 2]]
            i += 3
        else:
            i += 1
    path = history_path(opts["--history"])
    rows = bench_history_rows(path)
    if not rows:
        print(json.dumps({"check": "regression", "history": path,
                          "rows": 0, "regressions": [],
                          "note": "no trajectory yet"}), flush=True)
        return 0
    regs = check_regressions(
        path, window=int(opts["--window"]),
        min_prior=int(opts["--min-prior"]),
        tolerance=(float(opts["--tolerance"])
                   if opts["--tolerance"] is not None else None),
        directions=_BENCH_DIRECTIONS)
    series = {(r.get("leg"), r["metric"]) for r in rows}
    print(json.dumps({"check": "regression", "history": path,
                      "rows": len(rows), "series": len(series),
                      "regressions": regs}), flush=True)
    for r in regs:
        direction = ">" if r["direction"] == "lower" else "<"
        print(f"REGRESSION {r['leg']}/{r['metric']}: {r['value']} "
              f"{direction} trailing median {r['median']} beyond the "
              f"{r['tolerance']:.0%} band (ratio {r['ratio']}, "
              f"{r['n_prior']} prior points)", file=sys.stderr)
    if regs and profdiff_args:
        # a regression fired and two --profile artifacts were offered:
        # name the dominant frame/kernel delta (engine/profiler.py)
        try:
            from pathway_tpu.engine.profiler import diff_profiles

            with open(profdiff_args[0]) as f:
                a = json.load(f)
            with open(profdiff_args[1]) as f:
                b = json.load(f)
            diff = diff_profiles(a, b)
            dk, df = diff["dominant_kernel"], diff["dominant_frame"]
            if dk is not None:
                print(f"PROFDIFF dominant kernel: {dk['family']} "
                      f"{dk['device_ms_per_dispatch_a']} -> "
                      f"{dk['device_ms_per_dispatch_b']} ms/dispatch "
                      f"({dk['bound_by']}-bound)", file=sys.stderr)
            if df is not None:
                print(f"PROFDIFF dominant frame: {df['frame']} "
                      f"share {df['share_a']} -> {df['share_b']}",
                      file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — attribution is advisory
            print(f"PROFDIFF unavailable: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return 1 if regs else 0


# -- flight beacon -----------------------------------------------------------
# r05 lost a whole run to "backend probe hung past 240s" naming no operator,
# no leg, no last-dispatched kernel. The child process now mirrors its
# device-phase state (current stage, bridge depth, in-flight leg's operator
# + seconds-since-dispatch, via engine/flight_recorder.py) into a sidecar
# file every few seconds; the parent's hang/SIGTERM emit paths read it, so
# the surviving JSON line names the culprit.

_FLIGHT_STAGE: dict = {"stage": None, "started_at": None}


def _flight_file() -> str | None:
    return os.environ.get("_BENCH_FLIGHT_FILE") or None


def _set_stage(stage: str) -> None:
    _FLIGHT_STAGE["stage"] = stage
    _FLIGHT_STAGE["started_at"] = time.time()
    _write_flight_snapshot()


def _write_flight_snapshot() -> None:
    path = _flight_file()
    if not path:
        return
    try:
        from pathway_tpu.engine.device_bridge import live_bridge_snapshot
        from pathway_tpu.engine.flight_recorder import live_inflight

        started = _FLIGHT_STAGE["started_at"]
        snap = {
            "stage": _FLIGHT_STAGE["stage"],
            "stage_age_s": (round(time.time() - started, 1)
                            if started else None),
            "bridge": live_bridge_snapshot(),
            "inflight_op": live_inflight(),
            "updated_at": time.time(),
        }
        with open(path + ".tmp", "w") as f:
            json.dump(snap, f)
        os.replace(path + ".tmp", path)
    except Exception:  # noqa: BLE001 — the beacon must never kill a leg
        pass


def _start_flight_beacon(interval_s: float = 2.0) -> None:
    if not _flight_file():
        return
    import threading

    def run() -> None:
        while True:
            time.sleep(interval_s)
            _write_flight_snapshot()

    threading.Thread(target=run, daemon=True,
                     name="bench-flight-beacon").start()


def _flight_note() -> str | None:
    """One-line device-phase attribution from the sidecar file (None when
    no child ever wrote one)."""
    path = _flight_file()
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            snap = json.load(f)
    except Exception:  # noqa: BLE001 — a torn write must not mask the error
        return None
    parts = [f"stage={snap.get('stage')}"]
    if snap.get("stage_age_s") is not None:
        parts.append(f"{snap['stage_age_s']:.0f}s in stage")
    br = snap.get("bridge")
    if br:
        parts.append(f"bridge depth {br['depth']}/{br['max_inflight']}")
        leg = br.get("inflight")
        if leg:
            parts.append(f"leg tick {leg['tick']} dispatched "
                         f"{leg['since_s']:.1f}s ago")
    op = snap.get("inflight_op")
    if op and op.get("operator"):
        parts.append(f"in-flight op {op['operator']!r} [{op['leg']}] "
                     f"{op['since_s']:.1f}s since dispatch")
    age = time.time() - snap.get("updated_at", time.time())
    parts.append(f"(snapshot {age:.0f}s old)")
    return "; ".join(parts)


def _run_device_legs_child() -> None:
    """Child-process entry: backend init + the legs named in
    ``_BENCH_DEVICE_LEGS``. Prints a JSON snapshot line after EVERY leg
    (the parent takes the last parseable line), so a hang mid-leg can't
    discard an earlier completed measurement."""
    legs = [leg for leg in
            os.environ.get("_BENCH_DEVICE_LEGS", "").split(",")
            if leg and leg not in SKIP]
    # flight recorder on (unless explicitly off): the framework leg's
    # scheduler then exposes its in-flight operator to the beacon, so a
    # hang names the stuck operator instead of just "device phase"
    os.environ.setdefault("PATHWAY_FLIGHT_RECORDER", "1")
    _start_flight_beacon()
    result: dict = {}
    _set_stage("backend-init")
    try:
        import jax

        devs = jax.devices()  # first backend touch — may raise or hang
        result["n_devices"] = len(devs)
    except Exception as e:  # noqa: BLE001
        print(json.dumps(
            {"error": f"backend init failed: {type(e).__name__}: "
                      f"{str(e)[:300]}"}), flush=True)
        return
    print(json.dumps(result), flush=True)
    try:
        counter = _DeviceEventCounter()
    except Exception:  # noqa: BLE001 — counting must never kill a leg
        counter = None
    for leg in legs:
        _set_stage(leg)
        try:
            if counter is not None:
                leg_out, events = counter.count(leg, _LEG_FNS[leg])
                result.update(leg_out)
                result.update(events)
            else:
                result.update(_LEG_FNS[leg]())
        except Exception as e:  # noqa: BLE001
            result[f"{leg}_error"] = f"{type(e).__name__}: {str(e)[:300]}"
        if "framework_docs_per_s" in result and "docs_per_s" in result:
            # VERDICT #5's headline on the REAL device legs: framework-
            # path throughput over the raw-kernel leg's, SAME run —
            # target >= 0.85. Suffixed _device: the gated CPU autojit
            # leg owns the bare `framework_vs_raw_ratio` key, and a full
            # bench run must not let one leg clobber the other's number
            # in result/BENCH_LASTGOOD.json
            result["framework_vs_raw_ratio_device"] = round(
                result["framework_docs_per_s"] / result["docs_per_s"], 3)
        _set_stage(f"{leg}:done")
        print(json.dumps(result), flush=True)


def _probe_backend() -> str | None:
    """Return None when the device backend answers, else an error string.

    Retries are spread across the FULL device deadline window
    (``DEVICE_DEADLINE_S``), not a fixed try count: a tunnel that's
    unhealthy at one instant often recovers within minutes — round 4
    lost its whole TPU record to a single unhealthy window, and a
    fixed 4-try schedule still gave up after ~4 probe-timeouts while
    the deadline had most of its budget left. Delays grow 10s → 5min
    (capped) so a quick flap retries fast but a long outage doesn't
    burn the window on busy-waiting. ``BENCH_PROBE_TRIES`` survives as
    an optional hard cap for CI smoke runs."""
    import subprocess
    import sys

    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 240.0))
    max_tries = int(os.environ.get("BENCH_PROBE_TRIES", 0))  # 0: window
    deadline = time.monotonic() + float(
        os.environ.get("BENCH_PROBE_WINDOW", DEVICE_DEADLINE_S))
    probe_err = None
    delay = 10.0
    attempt = 0
    while True:
        attempt += 1
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                capture_output=True, text=True,
                timeout=min(probe_timeout,
                            max(10.0, deadline - time.monotonic())))
            if probe.returncode == 0:
                return None
            tail = probe.stderr.strip().splitlines()
            probe_err = f"backend probe rc={probe.returncode}: " \
                        + " | ".join(tail[-2:])
        except subprocess.TimeoutExpired:
            probe_err = (f"backend probe hung past {probe_timeout:.0f}s "
                         "(device tunnel unhealthy)")
        if max_tries and attempt >= max_tries:
            break
        remaining = deadline - time.monotonic()
        if remaining <= 10.0:  # not enough window left for another try
            break
        time.sleep(min(delay, remaining))
        delay = min(delay * 2.0, 300.0)
    return probe_err[:400]


def _run_leg_group(legs: list[str], timeout_s: float) -> dict:
    """Run one group of device legs in a killable subprocess.

    The first device touch on a tunneled dev chip can fail
    (``Unable to initialize backend 'axon'``) or block forever inside
    PJRT client setup, where neither SIGALRM nor Python-level retry can
    reach it — round 3's artifact died both ways. A subprocess with a
    hard timeout turns every failure mode into a JSON ``error`` field,
    and separate groups (embed vs knn vs serving) mean a hang in one
    cannot void the other's measurement.

    Child stdout is consumed INCREMENTALLY: the per-leg JSON snapshot
    lines are parsed as they arrive and each successful one is
    checkpointed to ``BENCH_LASTGOOD.json`` immediately (evidence rule —
    a wedged tunnel, or the outer driver's SIGKILL, can no longer erase
    a round's captured numbers).
    """
    import subprocess
    import sys
    import threading

    last_err = "device legs never ran"
    group_deadline = time.monotonic() + timeout_s  # total across tries
    for attempt in range(DEVICE_TRIES):
        try_budget = group_deadline - time.monotonic()
        if try_budget < 60.0:
            break
        env = dict(os.environ, _BENCH_DEVICE_CHILD="1",
                   _BENCH_DEVICE_LEGS=",".join(legs))
        proc = subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state: dict = {"last": None}
        stderr_tail: list[str] = []

        def _read_stdout(stdout=proc.stdout, state=state):
            for ln in stdout:
                s = ln.strip()
                if not s.startswith("{"):
                    continue
                try:
                    d = json.loads(s)
                except json.JSONDecodeError:
                    continue
                if isinstance(d, dict):
                    state["last"] = d
                    if "error" not in d:
                        _write_lastgood(d)

        def _read_stderr(stderr=proc.stderr, tail=stderr_tail):
            for ln in stderr:
                tail.append(ln.rstrip())
                del tail[:-8]

        t_out = threading.Thread(target=_read_stdout, daemon=True)
        t_err = threading.Thread(target=_read_stderr, daemon=True)
        t_out.start()
        t_err.start()
        timed_out = False
        try:
            proc.wait(timeout=try_budget)
        except subprocess.TimeoutExpired:
            timed_out = True
            proc.kill()
            proc.wait()
        t_out.join(5.0)
        t_err.join(5.0)
        out = state["last"]
        if timed_out:
            # completed legs survive a hang in a later leg (their
            # snapshots were already parsed AND written to lastgood);
            # the flight note names what was in flight at the kill
            note = _flight_note()
            suffix = f"; {note}" if note else ""
            if out is not None:
                out["device_hang_error"] = (
                    f"legs {legs} exceeded {timeout_s:.0f}s; "
                    f"kept legs completed before the hang{suffix}")
                return out
            last_err = (f"legs {legs} exceeded {timeout_s:.0f}s "
                        f"(backend hang?){suffix}")
            continue
        if out is not None:
            if "error" not in out:
                return out
            last_err = out["error"]
            continue
        last_err = (f"device-leg subprocess rc={proc.returncode}: "
                    + " | ".join(stderr_tail[-3:]))[:400]
    return {"error": last_err}


def _run_device_legs() -> dict:
    """Probe, then run embed(+framework) and knn as separately salvageable
    subprocess groups, all under one DEVICE_DEADLINE_S wall-clock budget."""
    deadline = time.monotonic() + DEVICE_DEADLINE_S
    probe_err = _probe_backend()
    if probe_err is not None:
        return {"error": probe_err}
    groups = [g for g in
              ([leg for leg in ("embed", "framework") if leg not in SKIP],
               [leg for leg in ("knn",) if leg not in SKIP],
               [leg for leg in ("serving",) if leg not in SKIP]) if g]
    result: dict = {}
    for group in groups:
        remaining = deadline - time.monotonic()
        if remaining < 60.0:
            result[f"{'_'.join(group)}_error"] = (
                f"device deadline ({DEVICE_DEADLINE_S:.0f}s) exhausted "
                "before this group ran")
            continue
        out = _run_leg_group(group, min(DEVICE_TIMEOUT_S, remaining))
        for k, v in out.items():
            if k in ("error", "device_hang_error"):
                result[f"{'_'.join(group)}_{k}"] = v
            else:
                result[k] = v
        # trajectory rows for the device phase too: whatever the group
        # captured before any hang joins the time series (error keys are
        # non-numeric and filtered by the appender)
        _append_bench_history("_".join(group), out)
    return result


def main() -> None:
    if "--check-regression" in sys.argv:
        # perf-trajectory watch: judge BENCH_HISTORY.jsonl instead of
        # running any leg (engine/fleet_observability.py)
        sys.exit(check_regression_main(sys.argv[1:]))
    if os.environ.get("_BENCH_DEVICE_CHILD"):
        _run_device_legs_child()
        return

    # opt-in persistent XLA cache (PATHWAY_COMPILATION_CACHE): repeat
    # bench runs on one machine skip every warmup compile
    from pathway_tpu.warmup import maybe_enable_compilation_cache

    maybe_enable_compilation_cache()

    if "--profile" in sys.argv:
        # continuous profiler ON for the whole run: cost-model hooks in
        # the legs feed the per-family aggregates; one profile epoch is
        # snapped per completed leg (_maybe_profile_epoch) and embedded
        # under the "profile" key of the emitted artifact
        from pathway_tpu.engine.profiler import (Profiler, current_profiler,
                                                 install_profiler)

        if current_profiler() is None:
            _prof = Profiler()
            install_profiler(_prof)
            _prof.start()
        os.environ.setdefault("PATHWAY_PROFILER", "1")  # child processes

    result: dict = {}
    errors: dict = {}

    # CPU legs first: they always produce numbers, and the minutes they
    # take give a flaky device tunnel time to recover before the probe
    if "etl" not in SKIP:
        try:
            leg_out = bench_etl()
            result.update(leg_out)
            _append_bench_history("etl", leg_out)
        except Exception as e:  # noqa: BLE001
            errors["etl_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    if "autojit" not in SKIP:
        # auto-jit leg (CPU-runnable): framework-vs-raw on the doc-scoring
        # pipeline, auto-jit on/off in the same artifact + the per-stage
        # flight-recorder breakdown (where the Table-path tax went)
        try:
            leg_out = bench_autojit()
            result.update(leg_out)
            _append_bench_history("autojit", leg_out)
            _write_lastgood({k: v for k, v in result.items()
                             if k.startswith(("autojit_", "framework_vs_"))})
        except Exception as e:  # noqa: BLE001
            errors["autojit_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    if "scaleout" not in SKIP:
        # exchange-plane scale-out leg (CPU-runnable): 4-process SPMD
        # cluster vs 1 process over both transports (shm slab ring / raw
        # tcp), etl_scaleout_efficiency under the cores-vs-workers
        # honesty rule, byte-identity, per-transport encdec cost
        try:
            leg_out = bench_scaleout()
            result.update(leg_out)
            _append_bench_history("scaleout", leg_out)
        except Exception as e:  # noqa: BLE001
            errors["scaleout_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    if "paging" not in SKIP:
        # paged-store leg (CPU-runnable): ingest stall across online
        # growth paged-vs-slab + ragged warmup compile count
        try:
            leg_out = bench_paging()
            result.update(leg_out)
            _append_bench_history("paging", leg_out)
        except Exception as e:  # noqa: BLE001
            errors["paging_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    if "durability" not in SKIP:
        # watermark-durability leg (CPU-runnable): bridge overlap with
        # persistence ON at inflight 1 vs 4 + checkpoint cadence — the
        # evidence that durability no longer prices pipelining at depth 1
        try:
            leg_out = bench_durability()
            result.update(leg_out)
            _append_bench_history("durability", leg_out)
        except Exception as e:  # noqa: BLE001
            errors["durability_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    if "recovery" not in SKIP:
        # bounded-recovery leg (CPU-runnable): restart wall-clock at
        # 1k/10k/100k-row histories, WAL-only (linear) vs snapshot+suffix
        # (~flat) — the evidence that compaction bounds restart by data
        # size, not stream age
        try:
            leg_out = bench_recovery()
            result.update(leg_out)
            _append_bench_history("recovery", leg_out)
        except Exception as e:  # noqa: BLE001
            errors["recovery_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    if "replica" not in SKIP:
        # replica-fleet leg (CPU-runnable): hydration time-to-ready vs
        # history size (WAL-only vs snapshot), end-to-end p50/p95 through
        # the router at 1 vs 2 replicas, staleness lag exported on
        # /metrics, and the kill-under-load failover count
        try:
            leg_out = bench_replica()
            result.update(leg_out)
            _append_bench_history("replica", leg_out)
        except Exception as e:  # noqa: BLE001
            errors["replica_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    if "qos" not in SKIP:
        # QoS leg (CPU-runnable): the same heavy-ingest serving workload
        # QoS-off vs QoS-on — the before/after artifact for "the
        # controller trades ingest throughput for query latency"
        # (engine/qos.py), plus visible-shedding / deferral / coalescing
        # counters from the induced overload phase
        try:
            leg_out = bench_qos()
            result.update(leg_out)
            _append_bench_history("qos", leg_out)
            _write_lastgood({k: v for k, v in leg_out.items()
                             if k.startswith("qos_")})
        except Exception as e:  # noqa: BLE001
            errors["qos_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    if "semantic_cache" not in SKIP:
        # semantic result-cache leg (CPU-runnable): the same Zipf query
        # stream through the router cache-off vs cache-on — served QPS,
        # p95, hit rates at both layers, invalidations/tick under live
        # ingest (engine/result_cache.py)
        try:
            leg_out = bench_semantic_cache()
            result.update(leg_out)
            _append_bench_history("semantic_cache", leg_out)
            _write_lastgood({k: v for k, v in leg_out.items()
                             if k.startswith("semantic_cache_")})
        except Exception as e:  # noqa: BLE001
            errors["semantic_cache_error"] = \
                f"{type(e).__name__}: {str(e)[:300]}"

    # sidecar path for the device-phase flight beacon, inherited by the
    # child processes; every emit below reads it, so the last surviving
    # JSON line always carries whatever attribution the child reported
    if not (_DEVICE_LEG_NAMES <= SKIP) \
            and "_BENCH_FLIGHT_FILE" not in os.environ:
        import tempfile

        os.environ["_BENCH_FLIGHT_FILE"] = os.path.join(
            tempfile.gettempdir(), f"bench_flight_{os.getpid()}.json")

    def emit(extra_error: str | None = None) -> None:
        # value/vs_baseline are null — not a real-looking 0.0 — when the
        # embed leg never produced a measurement
        docs_per_sec = result.get("docs_per_s")
        err = dict(errors)
        if extra_error:
            err["bench_error"] = extra_error
        note = _flight_note()
        if note:
            # device-phase attribution (stage, bridge depth, in-flight
            # leg's operator + seconds-since-dispatch) from the child's
            # flight beacon — see _flight_note
            err["device_phase"] = note
        extra = {}
        if _PROFILE_EPOCHS:
            # --profile: per-leg cost-model + flamegraph epochs, the
            # `python -m pathway_tpu profdiff` input
            extra["profile"] = _PROFILE_EPOCHS
        print(json.dumps({
            "metric": "RAG docs/sec/chip (embed+index); p50 KNN @10M",
            "value": None if docs_per_sec is None else round(docs_per_sec, 1),
            "unit": "docs/s",
            "vs_baseline": None if docs_per_sec is None else round(
                docs_per_sec / BASELINE_DOCS_PER_SEC_PER_CHIP, 3),
            **{k: v for k, v in result.items() if k != "docs_per_s"},
            **extra,
            **err,
        }), flush=True)

    # the CPU legs' numbers must survive ANYTHING the device phase does:
    # emit a snapshot now (the capture takes the LAST parseable line), and
    # emit again from a SIGTERM handler — a half-wedged tunnel can pass
    # the probe then hang a dispatch for hours, and an outer driver
    # timeout that SIGKILLs after SIGTERM must still find a JSON line
    # (round-5 rehearsal lost a whole run's output exactly this way)
    emit("device legs still pending" if not (
        _DEVICE_LEG_NAMES <= SKIP) else None)

    import signal

    def on_term(signum, frame):  # noqa: ARG001
        emit(f"terminated by signal {signum} during device legs")
        raise SystemExit(1)

    try:
        signal.signal(signal.SIGTERM, on_term)
    except (ValueError, OSError):
        pass  # non-main thread / exotic platform: snapshot above suffices

    if not (_DEVICE_LEG_NAMES <= SKIP):
        dev = _run_device_legs()
        for k, v in dev.items():
            (errors if k.endswith("error") else result)[k] = v

    emit()


def bench_embed() -> dict:
    """The docs/sec leg: tokenize → encoder forward → fused index add."""
    import jax

    from pathway_tpu.models.encoder import EncoderConfig, encode, init_params
    from pathway_tpu.models.hf_loader import find_local_checkpoint, load_model
    from pathway_tpu.models.tokenizer import (WordPieceTokenizer,
                                              make_synthetic_vocab)
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    # real BGE weights + vocab when the checkpoint is on disk; otherwise
    # random weights at the exact BGE shape and a synthetic vocab — the
    # tokenizer still runs the real WordPiece algorithm (native C++ batch
    # kernel), so the host-side cost is representative either way
    if find_local_checkpoint("BAAI/bge-small-en-v1.5"):
        params, config, tokenizer = load_model("BAAI/bge-small-en-v1.5")
        tokenizer.max_len = SEQ
    else:
        config = EncoderConfig.bge_small()
        params = init_params(jax.random.PRNGKey(0), config)
        tokenizer = WordPieceTokenizer(
            make_synthetic_vocab([f"word{i}" for i in range(4096)],
                                 vocab_size=config.vocab_size),
            max_len=SEQ)
    # fused ingest donates the slab, so capacity is pinned — reserve enough
    # for the whole timed window (bf16: 1M x 384 = 0.8 GB)
    index = BruteForceKnnIndex(config.hidden, reserved_space=1 << 20,
                               metric=KnnMetric.COS, dtype="bfloat16")

    import jax.numpy as jnp

    encode_fn = jax.jit(
        lambda p, ids, mask: encode(p, ids, mask, config=config))

    # ONE dispatch per batch: encode fused with the slab scatter, slab
    # donated — embeddings never leave the chip and nothing blocks.
    # Host→device payload is minimized: int16 token ids (vocab < 32768)
    # and per-row lengths instead of a (B, S) mask — the mask is rebuilt
    # on device with iota < len.
    def producer(p, ids_i16, lens):
        ids32 = ids_i16.astype(jnp.int32)
        mask = jnp.arange(ids32.shape[1])[None, :] < lens[:, None]
        return encode(p, ids32, mask, config=config)

    ingest = index.make_fused_ingest(producer)

    def pack(ids, mask):
        # bucket-pad to a multiple of 16 (bounded by SEQ): real docs do not
        # fill the max context, and MXU time scales with padded tokens —
        # a few shape buckets bound recompilation
        lens = mask.sum(axis=1).astype(np.int32)
        width = min(SEQ, max(16, int(-(-int(lens.max()) // 16) * 16)))
        return ids[:, :width].astype(np.int16), lens

    docs = make_docs(BATCH * 4)

    def run_batch(batch_docs, key_base):
        ids, mask = tokenizer.batch(batch_docs, pad_to=SEQ)
        ids16, lens = pack(ids, mask)
        ingest([Pointer(key_base + i) for i in range(len(batch_docs))],
               params, ids16, lens)

    # warmup (compile + device clock ramp) + correctness probe: a doc must
    # retrieve itself. Several post-compile batches: the first dispatches of
    # a fresh process run measurably slower.
    run_batch(docs[:BATCH], 0)
    for w in range(3):
        run_batch(docs[:BATCH], 0)
    ids, mask = tokenizer.batch(docs[:8], pad_to=SEQ)
    probe = np.asarray(encode_fn(params, ids, mask))
    res = index.search([(Pointer(10**9), probe[3], 1, None)])
    assert res and res[0] and res[0][0][0] == Pointer(3), \
        f"self-retrieval failed: {res}"

    # timed: pipeline host tokenization against device compute — submit the
    # encode for batch i, tokenize batch i+1 while the TPU works, then drain.
    # Metric = sustained docs/sec over the timed window (first timed batch
    # dropped: it straddles the warmup boundary). Sustained, not per-batch
    # median — the number must be comparable to BASELINE.md's sustained
    # target, stalls included.
    n_batches = 0
    key_base = BATCH
    start = time.perf_counter()
    batch_times = []
    batch_tokens = []
    batch_flops = []
    last_t = start
    ids16, lens = pack(*tokenizer.batch(docs[:BATCH], pad_to=SEQ))
    while True:
        ingest([Pointer(key_base + i) for i in range(BATCH)],
               params, ids16, lens)  # async: one fused dispatch
        batch_tokens.append(ids16.shape[0] * ids16.shape[1])
        batch_flops.append(batch_tokens[-1] * _encoder_flops_per_token(
            config, seq=ids16.shape[1]))
        next_docs = docs[((n_batches + 1) % 4) * BATCH:][:BATCH]
        ids16, lens = pack(*tokenizer.batch(next_docs, pad_to=SEQ))
        now = time.perf_counter()
        batch_times.append(now - last_t)
        last_t = now
        n_batches += 1
        key_base += BATCH
        elapsed = time.perf_counter() - start
        if (elapsed > 15.0 and len(batch_times) >= 8) or \
                key_base + BATCH > index.capacity:
            break
    # drain the async dispatch queue before the final stamp: sustained
    # throughput must include all queued device work, not just dispatches.
    # Materialize (not block_until_ready — a relay can report that ~0 ms):
    index.drain()
    now = time.perf_counter()
    batch_times[-1] += now - last_t
    sustained = batch_times[1:]  # drop the warmup-straddling first batch
    docs_per_sec = BATCH * len(sustained) / float(np.sum(sustained))
    tokens_per_sec = float(np.sum(batch_tokens[1:]) / np.sum(sustained))
    # MFU from per-batch flops at the ACTUAL padded width (not SEQ):
    # sustained MFU counts host stalls against the device
    mfu = float(np.sum(batch_flops[1:]) / np.sum(sustained)) \
        / (PEAK_TFLOPS * 1e12)
    mfu_dev = _device_only_mfu(params, config)

    # free the embed leg's device state (slab + donated buffers) before the
    # 10M KNN leg claims most of HBM
    del index, ingest
    import gc

    gc.collect()

    return {
        "docs_per_s": docs_per_sec,
        "tokens_per_s": round(tokens_per_sec, 0),
        "mfu_est": round(mfu, 3),
        "mfu_device_only": round(mfu_dev, 3),
        "mfu_peak_tflops": PEAK_TFLOPS,
    }


def _device_only_mfu(params, config, B: int = 2048, W: int = 128,
                     reps: int = 8) -> float:
    """Encoder MFU with NO host in the loop (reps forwards inside one
    jitted fori_loop): the program's device ceiling, reported next to
    sustained MFU so host-stall time is attributable. Measured r5 on
    v5e at (2048, 128): ~0.30 with erf-gelu, ~0.41-0.58 after the
    tanh-gelu swap (EncoderConfig.gelu — erf's lowering blocked XLA's
    MLP fusion; the swap is below bf16 quantization noise). XLA dense
    attention still beats the Pallas kernel at S=128 (ops/attention.py);
    the remaining gap to the ~0.63 matmul-skeleton ceiling is softmax +
    layernorm HBM traffic."""
    import jax
    import jax.numpy as jnp

    from pathway_tpu.models.encoder import encode

    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, config.vocab_size, (B, W)).astype(np.int32))
    lens = jnp.full((B,), W - 5, jnp.int32)

    @jax.jit
    def loop(params, ids, lens):
        def body(i, acc):
            mask = jnp.arange(ids.shape[1])[None, :] < lens[:, None]
            out = encode(params, ids + i, mask, config=config)
            return acc + jnp.sum(out).astype(jnp.float32)

        return jax.lax.fori_loop(0, reps, body, jnp.float32(0.0))

    float(loop(params, ids, lens))  # compile + warm
    # best of 3: this reports the program's device CEILING, and transient
    # chip contention can only subtract from it (observed 0.41-0.58
    # spread on the shared dev chip for identical code)
    dt = min(_timed(lambda: float(loop(params, ids, lens)))
             for _ in range(3))
    return reps * B * W * _encoder_flops_per_token(config, seq=W) \
        / dt / (PEAK_TFLOPS * 1e12)


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def bench_embed_framework(n_docs: int | None = None) -> dict:
    """BASELINE config 2 measured through the ACTUAL framework: a docs
    Table streamed tick-by-tick through VectorStoreServer's graph
    (parse UDF → flatten → split UDF → flatten → JaxEncoderEmbedder
    batch-UDF → engine external index add) under GraphRunner, with one
    retrieval query answered against the built index.

    Reference counterpart: xpacks/llm/vector_store.py:214-292
    (sources→parse→split→embed→index). ``framework_docs_per_s`` vs the
    raw-kernel ``docs_per_s`` is the engine overhead this round is
    shrinking; both ride the same encoder shape + WordPiece tokenizer.
    """
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.json import Json
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index,
    )
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    if n_docs is None:
        n_docs = int(os.environ.get("BENCH_FRAMEWORK_DOCS", BATCH * 8))
    n_ticks = max(1, n_docs // BATCH)

    emb = _make_framework_embedder(JaxEncoderEmbedder)

    G.clear()
    schema = sch.schema_from_types(data=str, _metadata=pw.Json)
    docs_rows = [(doc, Json({"path": f"/d{i}.txt"}),
                  (i * n_ticks) // n_docs * 2, 1)
                 for i, doc in enumerate(make_docs(n_docs))]
    docs = table_from_rows(schema, docs_rows, is_stream=True)

    store = VectorStoreServer(
        docs, embedder=emb,
        index_builder=lambda chunks: default_brute_force_knn_document_index(
            chunks.text, chunks, embedder=emb,
            dimensions=emb.get_embedding_dimension(),
            reserved_space=n_docs + 64, dtype="bfloat16"))
    qschema = sch.schema_from_types(
        query=str, k=int, metadata_filter=type(None),
        filepath_globpattern=type(None))
    queries = table_from_rows(
        qschema, [("word1 word2 word3", 3, None, None)])
    res = store.retrieve_query(queries)
    runner = GraphRunner()
    cap = runner.capture(res)

    # pre-compile the kernels at the exact shapes the timed run will use,
    # so the measurement is throughput, not XLA compile time (the raw leg
    # equally excludes its warmup dispatches). The fused encode+scatter
    # step is a separate jit function from the plain encoder, so warm it
    # through the BUILT engine index (then retract the warmup rows).
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import DeviceEmbeddingKnnIndex

    warm = make_docs(BATCH, seed=1)
    emb.embed_batch(["word1 word2 word3"])  # the (1, bucket) query shape
    # the timed ticks are contiguous BATCH-doc slices whose packed widths
    # can straddle a bucket boundary (48 vs 64): warm the fused kernel at
    # EVERY width the run will dispatch, or a ~0.75 s XLA compile lands
    # inside the timed window (measured r5: 2 in-window compiles cost
    # 1.48 s of a 2.76 s window)
    all_texts = [r[0] for r in docs_rows]
    widths = sorted({emb.pack_tokens(all_texts[t * BATCH:(t + 1) * BATCH])[0]
                     .shape[1] for t in range(n_ticks)})
    warmed_fused = False
    for node in runner.graph.nodes:
        idx = getattr(node.op, "index", None)
        if isinstance(idx, DeviceEmbeddingKnnIndex):
            wkeys = [Pointer((1 << 62) + i) for i in range(BATCH)]
            idx.add_batch(wkeys, warm)
            for w in widths:
                idx._fused(wkeys, emb.params,
                           np.zeros((BATCH, w), np.int16),
                           np.full(BATCH, max(1, w - 2), np.int32))
            # warm the top-k search kernel at the query fanout (k=3) —
            # the retrieval answer otherwise compiles it in-window
            idx.search([(Pointer((1 << 62) + BATCH),
                         "word1 word2 word3", 3, None)])
            for k in wkeys:
                idx.remove(k)
            # push the removal invalidations now: they sit in the dirty
            # set, and the first timed ingest would otherwise flush them
            # through the plain scatter — compiling it in-window (0.74 s)
            idx.inner.flush_device()
            warmed_fused = True
    if not warmed_fused:
        emb.embed_batch(warm)
        emb.embed_batch(warm)

    t0 = time.perf_counter()
    runner.run_batch(n_workers=1)
    # drain the async dispatch queue before the stamp (same contract as
    # the raw leg): the last ticks' fused ingests may still be queued
    for node in runner.graph.nodes:
        idx = getattr(node.op, "index", None)
        if isinstance(idx, DeviceEmbeddingKnnIndex):
            idx.inner.drain()  # materialize: relay-proof
    dt = time.perf_counter() - t0
    bridge = runner._scheduler.bridge_stats()
    G.clear()

    final = [row for _, row, _, diff in cap.events if diff > 0]
    assert final, "framework retrieval produced no output rows"
    reply = final[-1][0]
    matches = reply.value if hasattr(reply, "value") else reply
    assert matches, f"framework retrieval produced no matches: {reply!r}"
    from pathway_tpu.engine.device_bridge import device_inflight_from_env

    out = {
        "framework_docs_per_s": round(n_docs / dt, 1),
        "framework_n_docs": n_docs,
        "framework_ticks": n_ticks,
        # pipelined-execution instrumentation (engine/device_bridge.py):
        # legs > 0 proves the async path ran; overlap_ratio counts legs
        # that fully overlapped host work of later ticks. Same tolerant
        # parse as the runtime, so the label matches the mode measured.
        "framework_device_inflight": device_inflight_from_env(),
    }
    if bridge is not None:
        out["framework_bridge_legs"] = bridge["legs_resolved"]
        out["framework_bridge_overlap_ratio"] = round(
            bridge["overlap_ratio"], 3)
        out["framework_bridge_queue_wait_ms"] = bridge["queue_wait_ms"]
    try:
        # auto-jit tier counters for THIS run (internals/autojit.py):
        # fused programs, XLA bucket compiles, demotions, dispatch mix
        from pathway_tpu.internals.autojit import autojit_stats

        ajs = autojit_stats()
        out["framework_autojit_enabled"] = ajs["enabled"]
        out["framework_autojit_programs"] = ajs["programs"]
        out["framework_autojit_compiles"] = ajs["compiles"]
        out["framework_autojit_demotions"] = ajs["demotions"]
        out["framework_autojit_bucket_count"] = ajs["bucket_count"]
    except Exception:  # noqa: BLE001
        pass
    return out


def _make_framework_embedder(cls):
    """JaxEncoderEmbedder at the flagship shape: real BGE checkpoint when
    on disk, otherwise random weights at the exact BGE shape with the real
    WordPiece algorithm over a synthetic vocab (same policy as
    bench_embed). max_batch_size pins the per-dispatch shape so one
    compile serves the whole run."""
    import jax

    from pathway_tpu.models.encoder import EncoderConfig, init_params
    from pathway_tpu.models.hf_loader import find_local_checkpoint
    from pathway_tpu.models.tokenizer import (WordPieceTokenizer,
                                              make_synthetic_vocab)

    if find_local_checkpoint("BAAI/bge-small-en-v1.5"):
        return cls(model="BAAI/bge-small-en-v1.5", max_len=SEQ,
                   max_batch_size=BATCH)
    config = EncoderConfig.bge_small()
    return cls(
        config=config,
        params=init_params(jax.random.PRNGKey(0), config),
        tokenizer=WordPieceTokenizer(
            make_synthetic_vocab([f"word{i}" for i in range(4096)],
                                 vocab_size=config.vocab_size),
            max_len=SEQ),
        max_len=SEQ, max_batch_size=BATCH)


def bench_serving() -> dict:
    """Serving-path SLO leg: the BASELINE ``knn_p50_e2e_ms`` measured as
    a *serving* latency for the first time.

    Queries enter through a real ``rest_connector`` (HTTP POST), ride
    the commit tick into ``query_as_of_now`` against a KNN index that is
    ingesting vectors CONCURRENTLY, and resolve back through the
    response writer. The request tracker (engine/request_tracker.py)
    stamps every hand-off, so the reported e2e quantiles come with the
    full per-stage decomposition (ingress wait / queue / host leg /
    device leg / response write) — the input signal for the PR-7
    latency-aware admission scheduler.
    """
    import threading
    import urllib.request

    import pathway_tpu as pw
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.io.http import PathwayWebserver, rest_connector
    from pathway_tpu.io.python import ConnectorSubject
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index,
    )

    os.environ.setdefault("PATHWAY_FLIGHT_RECORDER", "1")  # tracker on
    G.clear()
    dim, n_vecs = SERVING_DIM, SERVING_N
    loaded = threading.Event()

    class IngestSubject(ConnectorSubject):
        """Bulk-load the slab, then keep trickling inserts so every
        timed query is answered under live ingest. Owns its generator —
        numpy Generators are not thread-safe, and this runs on the
        reader thread concurrently with the query thread's draws."""

        def run(self):
            rng = np.random.default_rng(1)
            chunk = 4096
            pushed = 0
            while pushed < n_vecs:
                m = min(chunk, n_vecs - pushed)
                for v in rng.random((m, dim), np.float32) * 2.0 - 1.0:
                    self.next(v=v)
                pushed += m
                if not self._session.sleep(0.002):
                    return
            loaded.set()
            while not self._session.stop_requested:
                for v in rng.random((64, dim), np.float32) * 2.0 - 1.0:
                    self.next(v=v)
                if not self._session.sleep(0.02):
                    return

    data = pw.io.python.read(
        IngestSubject(), schema=sch.schema_from_types(v=np.ndarray),
        autocommit_duration_ms=10, name="serving_ingest")
    index = default_brute_force_knn_document_index(
        data.v, data, dimensions=dim, reserved_space=n_vecs + (64 << 10),
        dtype="bfloat16")

    ws = PathwayWebserver(host="127.0.0.1", port=0)
    qschema = sch.schema_from_types(vec=dt.ANY, k=int)
    queries, writer = rest_connector(
        webserver=ws, route="/query", schema=qschema, methods=("POST",),
        delete_completed_queries=True, autocommit_duration_ms=5)
    qv = queries.select(
        qv=pw.apply(lambda v: np.asarray(v, dtype=np.float32),
                    queries.vec),
        k=queries.k)
    res = index.query_as_of_now(qv.qv, number_of_matches=qv.k)
    writer(res.select(
        n_matches=pw.apply(len, res._pw_index_reply_id)))

    errors: list[BaseException] = []

    def _run():
        try:
            pw.run()
        except Exception as e:  # noqa: BLE001 — reported in the leg JSON
            errors.append(e)

    th = threading.Thread(target=_run, daemon=True, name="bench-serving")
    th.start()
    try:
        deadline = time.monotonic() + 600.0
        rt = None
        while time.monotonic() < deadline and rt is None:
            live = list(_streaming._ACTIVE_RUNTIMES)
            if live and ws._started.is_set() and ws.port:
                rt = live[0]
            if errors:
                raise errors[0]
            time.sleep(0.05)
        assert rt is not None, "serving runtime never started"
        if not loaded.wait(timeout=max(60.0, deadline - time.monotonic())):
            raise TimeoutError(
                f"serving slab never finished loading ({n_vecs} vecs)")

        url = f"http://127.0.0.1:{ws.port}/query"

        def ask(vec) -> float:
            body = json.dumps({"vec": [float(x) for x in vec],
                               "k": 10}).encode()
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=120) as resp:
                resp.read()
            return (time.perf_counter() - t0) * 1e3

        qvecs = np.random.default_rng(2).random(
            (SERVING_WARMUP + SERVING_QUERIES, dim),
            np.float32) * 2.0 - 1.0
        tracker = rt.recorder.requests
        for i in range(SERVING_WARMUP):  # compile + slab upload
            ask(qvecs[i])
        n_warm = tracker.count  # completions before the timed window
        client_ms = [ask(qvecs[SERVING_WARMUP + i])
                     for i in range(SERVING_QUERIES)]
        # count-based slice: the completed ring is bounded, so indexing
        # from its front would misalign once warmup spans are evicted —
        # take exactly the timed window's completions off the tail
        n_timed = tracker.count - n_warm
        spans = tracker.trace_spans()[-n_timed:] if n_timed else []
        assert spans, "no timed request spans completed"
        if len(spans) < n_timed:
            print(f"serving: completed-span ring kept {len(spans)} of "
                  f"{n_timed} timed spans (raise "
                  "PATHWAY_REQUEST_TRACE_SPANS for larger windows)",
                  flush=True)
        ingested = sum(
            st.get("insertions", 0)
            for nid, st in rt.scheduler.stats.items()
            if rt.runner.graph.nodes[nid].name == "serving_ingest")
    finally:
        _streaming.stop_all()
        th.join(15.0)
        G.clear()
    if errors:
        raise errors[0]

    e2e = np.array([r["e2e_ms"] for r in spans])
    # SLO accounting over the TIMED window only — the run-wide tracker
    # also counted the warmup queries (XLA compile, slab upload), which
    # would misstate the serving result in the headline fields
    over_budget = int(np.sum(e2e > tracker.slo_ms))
    out = {
        # exact quantiles over the timed window (warmup excluded)
        "knn_p50_e2e_ms": round(float(np.percentile(e2e, 50)), 2),
        "knn_p95_e2e_ms": round(float(np.percentile(e2e, 95)), 2),
        "knn_p99_e2e_ms": round(float(np.percentile(e2e, 99)), 2),
        "serving_client_p50_ms": round(float(np.percentile(client_ms, 50)),
                                       2),
        "serving_n_queries": len(spans),
        "serving_n_vectors": n_vecs,
        "serving_ingested_rows": int(ingested),
        "serving_dim": dim,
        "serving_slo_ms": tracker.slo_ms,
        "serving_slo_burn_rate": round(
            (over_budget / len(e2e)) / tracker.error_budget, 3),
        "serving_over_budget": over_budget,
    }
    from pathway_tpu.engine.request_tracker import STAGES

    for stage in STAGES:
        vals = np.array([r["stages"][stage] for r in spans])
        out[f"serving_stage_{stage}_p50_ms"] = round(
            float(np.percentile(vals, 50)), 3)
    return out


def _qos_serving_phase(qos_on: bool) -> dict:
    """One phase of the QoS before/after: a KNN index under HEAVY live
    ingest (large chunks per commit tick, so the device leg is dominated
    by maintenance work) serving closed-loop rest queries. Returns the
    phase's query quantiles, the ingest rate observed DURING the timed
    query window, and — QoS on — the controller's counters."""
    import concurrent.futures
    import threading
    import urllib.error
    import urllib.request

    import pathway_tpu as pw
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.io.http import PathwayWebserver, rest_connector
    from pathway_tpu.io.python import ConnectorSubject
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index,
    )

    os.environ["PATHWAY_FLIGHT_RECORDER"] = "1"
    os.environ.setdefault("PATHWAY_SLO_E2E_MS", "20")
    if qos_on:
        os.environ["PATHWAY_QOS"] = "1"
        # a small admission queue so the induced overload burst below
        # actually sheds (visible-shedding evidence, never silent)
        os.environ.setdefault("PATHWAY_QOS_ADMISSION_QUEUE", "8")
    else:
        os.environ["PATHWAY_QOS"] = "0"
    G.clear()
    dim, n_vecs, chunk = QOS_DIM, QOS_N, QOS_INGEST_CHUNK
    loaded = threading.Event()

    class HeavyIngest(ConnectorSubject):
        """Bulk-load the slab, then keep pushing LARGE chunks at a
        heavy-but-sustainable rate — big enough that an unbudgeted tick
        spends tens of ms on maintenance (queries blow the SLO), small
        enough that the engine can keep up (an overload beyond machine
        capacity grows the backlog without bound and measures nothing
        but the backlog)."""

        def run(self):
            rng = np.random.default_rng(7)
            pushed = 0
            while pushed < n_vecs:
                m = min(chunk, n_vecs - pushed)
                for v in rng.random((m, dim), np.float32) * 2.0 - 1.0:
                    self.next(v=v)
                pushed += m
                if not self._session.sleep(0.002):
                    return
            loaded.set()
            while not self._session.stop_requested:
                for v in rng.random((chunk, dim), np.float32) * 2.0 - 1.0:
                    self.next(v=v)
                if not self._session.sleep(QOS_INGEST_PERIOD_S):
                    return

    data = pw.io.python.read(
        HeavyIngest(), schema=sch.schema_from_types(v=np.ndarray),
        autocommit_duration_ms=QOS_COMMIT_MS, name="qos_ingest")
    index = default_brute_force_knn_document_index(
        data.v, data, dimensions=dim, reserved_space=n_vecs + (256 << 10))
    ws = PathwayWebserver(host="127.0.0.1", port=0)
    qschema = sch.schema_from_types(vec=dt.ANY, k=int)
    queries, writer = rest_connector(
        webserver=ws, route="/query", schema=qschema, methods=("POST",),
        delete_completed_queries=True,
        autocommit_duration_ms=QOS_COMMIT_MS)
    qv = queries.select(
        qv=pw.apply(lambda v: np.asarray(v, dtype=np.float32),
                    queries.vec),
        k=queries.k)
    res = index.query_as_of_now(qv.qv, number_of_matches=qv.k)
    writer(res.select(
        n_matches=pw.apply(len, res._pw_index_reply_id)))

    errors: list[BaseException] = []

    def _run():
        try:
            pw.run()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    th = threading.Thread(target=_run, daemon=True,
                          name=f"bench-qos-{'on' if qos_on else 'off'}")
    th.start()
    out: dict = {}
    try:
        deadline = time.monotonic() + 300.0
        rt = None
        while time.monotonic() < deadline and rt is None:
            live = list(_streaming._ACTIVE_RUNTIMES)
            if live and ws._started.is_set() and ws.port:
                rt = live[0]
            if errors:
                raise errors[0]
            time.sleep(0.05)
        assert rt is not None, "qos runtime never started"
        assert (rt.qos is not None) == qos_on
        if not loaded.wait(timeout=max(60.0,
                                       deadline - time.monotonic())):
            raise TimeoutError(f"qos slab never loaded ({n_vecs} vecs)")
        url = f"http://127.0.0.1:{ws.port}/query"

        def ask(vec, timeout=120.0, retries=8):
            body = json.dumps({"vec": [float(x) for x in vec],
                               "k": QOS_K}).encode()
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            for _attempt in range(retries + 1):
                try:
                    with urllib.request.urlopen(req,
                                                timeout=timeout) as resp:
                        resp.read()
                    return
                except urllib.error.HTTPError as e:
                    e.read()
                    if e.code != 503 or _attempt == retries:
                        raise
                    # the shed contract: back off per Retry-After (capped
                    # — a closed-loop bench client is exactly who the
                    # hint is for)
                    try:
                        after = float(e.headers.get("Retry-After") or 1)
                    except ValueError:
                        after = 1.0
                    time.sleep(min(after, 1.0))

        def ingested_rows() -> int:
            return sum(
                st.get("insertions", 0)
                for nid, st in rt.scheduler.stats.items()
                if rt.runner.graph.nodes[nid].name == "qos_ingest")

        qvecs = np.random.default_rng(11).random(
            (QOS_WARMUP + QOS_QUERIES, dim), np.float32) * 2.0 - 1.0
        tracker = rt.recorder.requests
        for i in range(QOS_WARMUP):  # compile + slab upload
            ask(qvecs[i])
        # -- timed closed-loop window (sequential, under live ingest) ----
        n_warm = tracker.count
        rows0 = ingested_rows()
        t0 = time.perf_counter()
        for i in range(QOS_QUERIES):
            ask(qvecs[QOS_WARMUP + i])
        window_s = time.perf_counter() - t0
        rows1 = ingested_rows()
        n_timed = tracker.count - n_warm
        spans = tracker.trace_spans()[-n_timed:] if n_timed else []
        assert spans, "no timed qos request spans completed"
        e2e = np.array([r["e2e_ms"] for r in spans])
        tag = "on" if qos_on else "off"
        out[f"qos_{tag}_knn_p50_e2e_ms"] = round(
            float(np.percentile(e2e, 50)), 2)
        out[f"qos_{tag}_knn_p95_e2e_ms"] = round(
            float(np.percentile(e2e, 95)), 2)
        out[f"qos_{tag}_ingest_rate_rps"] = round(
            (rows1 - rows0) / max(window_s, 1e-9), 1)
        out[f"qos_{tag}_n_queries"] = len(spans)
        # -- induced overload: a concurrent burst past the queue cap -----
        def burst_one(i):
            """(got_503, retry_after_present) — summed on the main
            thread so concurrent increments cannot race."""
            try:
                ask(qvecs[i % len(qvecs)], timeout=60.0)
                return (0, False)
            except urllib.error.HTTPError as e:
                e.read()
                if e.code == 503:
                    return (1, bool(e.headers.get("Retry-After")))
                return (0, False)

        with concurrent.futures.ThreadPoolExecutor(
                max_workers=QOS_BURST) as pool:
            burst = list(pool.map(burst_one, range(QOS_BURST)))
        shed_503 = sum(b for b, _ra in burst)
        retry_after_seen = any(ra for _b, ra in burst)
        out[f"qos_{tag}_burst_503s"] = shed_503
        if qos_on:
            q = rt.qos.summary()
            out["qos_shed_total"] = q["shed_total"]
            out["qos_ingest_deferrals"] = q["ingest_deferrals"]
            out["qos_deferred_rows_total"] = q["deferred_rows_total"]
            out["qos_coalesced_dispatches"] = q["coalesced_dispatches"]
            out["qos_coalesced_queries"] = q["coalesced_queries"]
            out["qos_query_budget_ms"] = q["query_budget_ms"]
            assert retry_after_seen or shed_503 == 0, \
                "503 without Retry-After violates the shed contract"
    finally:
        _streaming.stop_all()
        th.join(15.0)
        G.clear()
        os.environ.pop("PATHWAY_QOS", None)
    if errors:
        raise errors[0]
    return out


def bench_qos() -> dict:
    """QoS before/after leg: the SAME heavy-ingest serving workload with
    the controller off, then on. The artifact shows the trade the
    ROADMAP item demands: QoS-on lowers query p50 (budgeted device time,
    admission control, coalescing) at the cost of measurably deferred
    ingest; QoS-off runs ingest at full rate while query latency blows
    out. Plus the shed evidence: the induced overload burst sheds
    visibly (503 + Retry-After + shed_total), never silently."""
    out = _qos_serving_phase(qos_on=False)
    out.update(_qos_serving_phase(qos_on=True))
    if out.get("qos_off_knn_p50_e2e_ms"):
        out["qos_p50_speedup"] = round(
            out["qos_off_knn_p50_e2e_ms"]
            / max(out["qos_on_knn_p50_e2e_ms"], 1e-9), 3)
    if out.get("qos_off_ingest_rate_rps"):
        out["qos_ingest_trade_ratio"] = round(
            out["qos_on_ingest_rate_rps"]
            / max(out["qos_off_ingest_rate_rps"], 1e-9), 3)
    return out


def _semantic_cache_phase(cache_on: bool) -> dict:
    """One phase of the semantic-cache before/after: a router-fronted
    single-member fleet (the _ReplicaFleet harness) under a Zipf query
    stream with the member's trickle ingest live. Cache-on enables BOTH
    layers — the operator cache in the serving process
    (PATHWAY_RESULT_CACHE) and the router's fleet cache on the query
    route (PATHWAY_ROUTER_CACHE_ROUTES) — because that is the shipped
    configuration; the router layer serves repeated bodies without
    touching the member, the operator layer serves repeated vectors
    without a kernel dispatch."""
    import http.client
    import tempfile
    import threading as _threading

    tag = "on" if cache_on else "off"
    prior = {k: os.environ.get(k)
             for k in ("PATHWAY_RESULT_CACHE",
                       "PATHWAY_ROUTER_CACHE_ROUTES")}
    os.environ["PATHWAY_RESULT_CACHE"] = "1" if cache_on else "0"
    if cache_on:
        os.environ["PATHWAY_ROUTER_CACHE_ROUTES"] = "/q"
    else:
        os.environ.pop("PATHWAY_ROUTER_CACHE_ROUTES", None)
    out: dict = {}
    try:
        with tempfile.TemporaryDirectory() as tmp:
            fleet = _ReplicaFleet(tmp, vecs=SEM_VECS,
                                  query_cost_ms=SEM_COST_MS)
            fleet.base_env["PATHWAY_RESULT_CACHE"] = \
                os.environ["PATHWAY_RESULT_CACHE"]
            fleet.base_env["REPLICA_BENCH_TRICKLE_S"] = str(SEM_TRICKLE_S)
            try:
                fleet.start_router()
                fleet.start_primary(register=True)
                ep = None
                deadline = time.monotonic() + 120.0
                while time.monotonic() < deadline and ep is None:
                    eps = [e for e in fleet.router.endpoints() if e.port]
                    ep = eps[0] if eps else None
                    time.sleep(0.05)
                assert ep is not None, "primary never registered"
                fleet._warm(ep)
                if cache_on:
                    # the watermark needs a version-carrying heartbeat
                    # before the router can serve (or fill) a single hit
                    deadline = time.monotonic() + 30.0
                    while time.monotonic() < deadline \
                            and fleet.router._fleet_watermark() is None:
                        time.sleep(0.05)
                    assert fleet.router._fleet_watermark() is not None, \
                        "index-version watermark never went live"
                # pre-encoded Zipf pool: identical bodies byte-for-byte,
                # which is exactly what the router cache keys on
                rng = np.random.default_rng(23)
                pool = rng.random((SEM_POOL, 16), np.float32) * 2 - 1
                bodies = [json.dumps({"vec": [float(x) for x in v],
                                      "k": 3}).encode() for v in pool]
                samples: list[tuple[float, float, bool]] = []
                lock = _threading.Lock()
                stop_at = time.monotonic() + SEM_WARMUP_S + SEM_SECONDS

                def client(seed: int):
                    crng = np.random.default_rng(1000 + seed)
                    while time.monotonic() < stop_at:
                        body = bodies[min(int(crng.zipf(SEM_ZIPF_S)) - 1,
                                          SEM_POOL - 1)]
                        t0 = time.monotonic()
                        ok = False
                        try:
                            conn = http.client.HTTPConnection(
                                "127.0.0.1", fleet.router.port,
                                timeout=60)
                            try:
                                conn.request(
                                    "POST", "/q", body=body,
                                    headers={"Content-Type":
                                             "application/json"})
                                resp = conn.getresponse()
                                resp.read()
                                ok = resp.status == 200
                            finally:
                                conn.close()
                        except OSError:
                            ok = False
                        with lock:
                            samples.append(
                                (t0, (time.monotonic() - t0) * 1e3, ok))

                threads = [_threading.Thread(target=client, args=(i,),
                                             daemon=True)
                           for i in range(SEM_CLIENTS)]
                t_start = time.monotonic()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=SEM_WARMUP_S + SEM_SECONDS + 120)
                cut = t_start + SEM_WARMUP_S
                timed = [(t0, ms, ok) for t0, ms, ok in samples
                         if t0 >= cut]
                lat = sorted(ms for _t0, ms, ok in timed if ok)
                assert lat, f"semantic-cache {tag} phase served nothing"
                window_s = max(t0 for t0, _ms, _ok in timed) - cut
                out[f"semantic_cache_{tag}_served_qps"] = round(
                    len(lat) / max(window_s, 1e-9), 1)
                out[f"semantic_cache_{tag}_p95_ms"] = round(
                    float(np.percentile(lat, 95)), 3)
                out[f"semantic_cache_{tag}_p50_ms"] = round(
                    float(np.percentile(lat, 50)), 3)
                out[f"semantic_cache_{tag}_queries"] = len(lat)
                out[f"semantic_cache_{tag}_lost"] = sum(
                    1 for _t0, _ms, ok in samples if not ok)
                if cache_on:
                    rc = fleet.router.response_cache.stats()
                    total = rc["hits"] + rc["misses"]
                    out["semantic_cache_router_hit_rate"] = round(
                        rc["hits"] / max(total, 1), 4)
                    out["semantic_cache_router_invalidations"] = \
                        rc["invalidations"]
                    # operator-layer stats ride the last heartbeat
                    opstats = ep.result_cache or {}
                    out["semantic_cache_op_hit_ratio"] = \
                        opstats.get("hit_ratio")
                    out["semantic_cache_invalidations_per_tick"] = \
                        opstats.get("invalidations_per_tick")
            finally:
                fleet.stop()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def bench_semantic_cache() -> dict:
    """Semantic result-cache leg (engine/result_cache.py): the same
    Zipf-distributed query stream against the same router-fronted
    serving member, cache-off then cache-on. The artifact the ROADMAP
    item demands: served QPS up at equal-or-better p95 (router hits
    never touch the member; operator hits never touch the device),
    with the hit/invalidation economics — hit rates at both layers and
    the member's invalidations-per-tick under its live trickle ingest
    — in the same snapshot."""
    out = _semantic_cache_phase(cache_on=False)
    out.update(_semantic_cache_phase(cache_on=True))
    if out.get("semantic_cache_off_served_qps"):
        out["semantic_cache_qps_speedup"] = round(
            out["semantic_cache_on_served_qps"]
            / max(out["semantic_cache_off_served_qps"], 1e-9), 3)
    return out


def bench_etl(n_rows: int = 100_000) -> dict:
    """Streaming ETL rows/sec: WordCount + dimension join over 50 ticks
    (the reference's headline WordCount benchmark shape, README.md:244-250),
    at n_workers ∈ {1, 8}.

    Measured finding (updated r4): the columnar stateful path took 1w from
    ~38k to ~190k rows/s on this box — dictionary-encoded group keys +
    int64 array reducer state (ColumnarGroupByOperator), raw-value join
    keys, and native (C, Python-C-API) passes for the join bilinear update
    and the groupby gather/emit loops (native/fastjoin.cpp,
    native/fastgroup.cpp). True multi-process execution
    (engine/multiproc.py — columnar wire frames over tcp or same-host
    shared memory, PATHWAY_PROCESSES xT) is correctness-tested
    (tests/test_sharded.py, tests/test_cli.py) and has its own
    ``scaleout`` leg (bench_scaleout) measuring etl_scaleout_efficiency
    under the cores-vs-workers honesty rule; this leg's in-process
    n_workers figures measure sharded scheduling on one interpreter,
    where wall-clock scaling is unobservable on a 1-core container
    (etl_n_cores below).
    """
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    n_ticks, vocab = 50, 5000
    rng = np.random.default_rng(0)
    words = rng.integers(0, vocab, size=n_rows)
    qtys = rng.integers(1, 10, size=n_rows)
    ticks = np.sort(rng.integers(0, n_ticks, size=n_rows))

    def bench_exchange() -> dict:
        """Serialization microbench of the multiprocess exchange plane
        (engine/wire.py): bytes/row and enc+dec cost of the columnar wire
        format actually sent between cluster processes.

        Methodology note — the r04→r05 "regression" (1.453 → 6.495
        µs/row) was this microbench timing ONE encode+decode: decode
        allocates tens of thousands of objects, so whenever a
        generational GC pass (gen-2 scans the whole live heap, huge
        after the earlier bench legs) landed inside the single timed
        window the number exploded. Best-of-5 is immune to that class;
        the single-trial figure is still reported for contrast, and
        tests/test_exchange_perf.py pins the best-of-5 ≤ 3.0 absolute."""
        from pathway_tpu.engine import wire
        from pathway_tpu.internals.keys import hash_values

        n = min(20_000, n_rows)
        ents = [(hash_values("row", i), (f"w{words[i]}", int(qtys[i])), 1)
                for i in range(n)]
        payload = {"rows": {0: {0: ents}}, "wm": None, "bcast": None}
        trials = []
        blob = b""
        for _ in range(5):
            t0 = time.perf_counter()
            chunks, _total, _rows = wire.encode_frame(("x", 1, 0), payload)
            blob = b"".join(chunks)
            mid = time.perf_counter()
            wire.decode_frame(blob)
            trials.append((mid - t0, time.perf_counter() - mid))
        best = min(trials, key=sum)
        sums_us = [(e + d) / n * 1e6 for e, d in trials]
        return {
            "exchange_bytes_per_row": round(len(blob) / n, 1),
            "exchange_encode_us_per_row": round(best[0] / n * 1e6, 3),
            "exchange_decode_us_per_row": round(best[1] / n * 1e6, 3),
            "exchange_encdec_us_per_row": round(min(sums_us), 3),
            # the old (r05) methodology and the spread, kept so the
            # artifact itself shows why single-trial numbers were noise
            "exchange_encdec_us_per_row_single_trial": round(
                sums_us[0], 3),
            "exchange_encdec_us_per_row_worst": round(max(sums_us), 3),
        }

    def run_once(n_workers: int) -> tuple[float, int]:
        G.clear()

        class S(pw.Schema):
            word: str
            qty: int

        class L(pw.Schema):
            word: str
            cat: str

        events = table_from_rows(
            S, [(f"w{words[i]}", int(qtys[i]), int(ticks[i]) * 2, 1)
                for i in range(n_rows)], is_stream=True)
        lex = table_from_rows(
            L, [(f"w{i}", f"cat{i % 7}") for i in range(vocab)])
        counts = events.groupby(events.word).reduce(
            events.word, n=pw.reducers.count(),
            total=pw.reducers.sum(events.qty))
        joined = counts.join(lex, counts.word == lex.word).select(
            counts.word, counts.n, counts.total, lex.cat)
        runner = GraphRunner()
        runner.capture(joined)
        t0 = time.perf_counter()
        runner.run_batch(n_workers=n_workers)
        dt = time.perf_counter() - t0
        # coalesced BSP rounds a cluster would pay per tick (the batched
        # exchange groups per-node barriers by topological level)
        rounds = runner._scheduler.exchange_rounds_per_tick()
        G.clear()
        return n_rows / dt, rounds

    def run_windowed() -> float:
        """Tumbling-window aggregation throughput (temporal hot path:
        arithmetic window assignment + columnar groupby)."""
        G.clear()

        class S(pw.Schema):
            sensor: str
            v: int
            at: int

        at_col = np.sort(rng.integers(0, n_rows // 10, size=n_rows))
        t = table_from_rows(
            S, [(f"s{words[i] % 200}", int(qtys[i]), int(at_col[i]),
                 int(ticks[i]) * 2, 1) for i in range(n_rows)],
            is_stream=True)
        win = pw.temporal.windowby(
            t, t.at, window=pw.temporal.tumbling(100), instance=t.sensor,
        ).reduce(sensor=pw.this._pw_instance,
                 start=pw.this._pw_window_start,
                 s=pw.reducers.sum(pw.this.v), c=pw.reducers.count())
        runner = GraphRunner()
        runner.capture(win)
        t0 = time.perf_counter()
        runner.run_batch(n_workers=1)
        dt = time.perf_counter() - t0
        G.clear()
        return n_rows / dt

    cores = os.cpu_count() or 1
    r1, exchange_rounds = run_once(1)
    r8, _ = run_once(8)
    # honest scaling presentation: an 8-worker figure on fewer than 8
    # cores measures timesharing, not scaling — label it so (round-4
    # reviewer note), and report a per-core figure from a fit run
    fit_workers = min(8, cores)
    out = {
        "etl_rows_per_s_1w": round(r1, 0),
        "etl_rows_per_s_8w": round(r8, 0),
        "etl_8w_oversubscribed": cores < 8,
        "etl_windowed_rows_per_s": round(run_windowed(), 0),
        "etl_n_rows": n_rows,
        "etl_ticks": n_ticks,
        "etl_n_cores": cores,
        # cluster barrier count per tick AFTER coalescing (BSP rounds;
        # was = exchanged nodes before the batched exchange landed)
        "etl_exchange_rounds_per_tick": exchange_rounds,
        **bench_exchange(),
    }
    if fit_workers > 1:
        rN, _ = run_once(fit_workers) if fit_workers != 8 else (r8, 0)
        out[f"etl_rows_per_s_{fit_workers}w"] = round(rN, 0)
        out["etl_rows_per_s_per_core"] = round(rN / fit_workers, 0)
    else:
        out["etl_rows_per_s_per_core"] = round(r1, 0)
    return out


_SCALEOUT_PROGRAM = """
import json, os, sys, time
import numpy as np
import pathway_tpu as pw
from pathway_tpu.debug import table_from_rows
from pathway_tpu.engine.multiproc import get_cluster
from pathway_tpu.internals.runner import GraphRunner

n_rows = int(os.environ["BENCH_SCALEOUT_ROWS"])
n_ticks = int(os.environ["BENCH_SCALEOUT_TICKS"])
vocab = 5000
rng = np.random.default_rng(0)
words = rng.integers(0, vocab, size=n_rows)
qtys = rng.integers(1, 10, size=n_rows)
ticks = np.sort(rng.integers(0, n_ticks, size=n_rows))

class S(pw.Schema):
    word: str
    qty: int

class L(pw.Schema):
    word: str
    cat: str

events = table_from_rows(
    S, [(f"w{words[i]}", int(qtys[i]), int(ticks[i]) * 2, 1)
        for i in range(n_rows)], is_stream=True)
lex = table_from_rows(
    L, [(f"w{i}", f"cat{i % 7}") for i in range(vocab)])
counts = events.groupby(events.word).reduce(
    events.word, n=pw.reducers.count(),
    total=pw.reducers.sum(events.qty))
joined = counts.join(lex, counts.word == lex.word).select(
    counts.word, counts.n, counts.total, lex.cat)
runner = GraphRunner()
cap = runner.capture(joined)
cl = get_cluster()
t0 = time.perf_counter()
runner.run_batch(cluster=cl)
dt = time.perf_counter() - t0
events_out = sorted((int(k), repr(r), t, d)
                    for k, r, t, d in cap.consolidated_events())
doc = {
    "dt_s": dt,
    "events": events_out,
    "rounds_per_tick": runner._scheduler.exchange_rounds_per_tick(),
    "stats": cl.stats if cl is not None else None,
    "by_transport": cl.stats_by_transport if cl is not None else None,
    "transports": cl.transport_counts() if cl is not None else {},
}
with open(sys.argv[1], "w") as f:
    json.dump(doc, f)
"""


# -- auto-jit leg (CPU-runnable) --------------------------------------------
# Per-doc "embed" payload for the framework-vs-raw comparison: a jitted
# id-embedding + 2-layer MLP + L2 norm, calibrated into the flagship
# raw-kernel budget's band (BASELINE 15k docs/s/chip ~ 66 us/doc; these
# dims measure ~57 us/doc on this container's CPU) so the ratio gates the
# SAME regime VERDICT #5's 10.1k-vs-15.0k numbers come from. A near-zero
# payload would gate pure dispatch overhead (a regime the real pipeline
# never runs in); an oversized one would hide any framework tax — the
# per-stage breakdown below keeps the tax itself visible either way.
AUTOJIT_DOCS = int(os.environ.get("BENCH_AUTOJIT_DOCS", 16 * 2048))
AUTOJIT_TICK = 2048
_AUTOJIT_VOCAB, _AUTOJIT_EMB, _AUTOJIT_H1, _AUTOJIT_H2 = \
    4096, 768, 1536, 1280


def _autojit_payload():
    """(embed_fn(ids int32[n]) -> float64[n], params) — the jitted raw
    kernel both sides of the comparison dispatch per tick."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(12)
    params = tuple(
        np.asarray(rng.standard_normal(s), np.float32) / np.sqrt(s[0])
        for s in ((_AUTOJIT_VOCAB, _AUTOJIT_EMB),
                  (_AUTOJIT_EMB, _AUTOJIT_H1), (_AUTOJIT_H1, _AUTOJIT_H2)))

    @jax.jit
    def fwd(ids, emb, w1, w2):
        h = jnp.tanh(emb[ids] @ w1)
        o = h @ w2
        return jnp.sqrt((o * o).sum(axis=1))

    def embed(ids: np.ndarray) -> np.ndarray:
        return np.asarray(fwd(jnp.asarray(ids), *params), np.float64)

    return embed


def bench_autojit(n_docs: int | None = None) -> dict:
    """Framework-vs-raw on CPU: the SAME doc-scoring pipeline measured as
    (a) raw kernels + a thin hand-written loop, (b) the Table path with
    auto-jit ON, (c) the Table path with auto-jit OFF (today's behavior).

    The pipeline carries every workload class the auto-jit tier targets:
    a chain of traceable/vmappable scalar UDFs (fused into one dispatch;
    interpreted per-row when OFF), a host-only UDF (split out and stepped
    on the host thread while the device leg is in flight, WindVE-style),
    and a batch device UDF payload (the jitted embed kernel) riding the
    pipelined bridge. The raw comparator dispatches the IDENTICAL jitted
    kernel and vectorized numpy score math per tick, with the host-only
    formatting as a plain Python loop — i.e. what a user would hand-write
    without the framework, including the row<->column conversions both
    sides must do.

    ``framework_vs_raw_ratio`` (VERDICT #5, target >= 0.85) is the ON
    ratio; ``framework_vs_raw_ratio_nojit`` reproduces today's gap in the
    same artifact. Per-stage flight-recorder breakdowns for both modes
    ship inline (`autojit_stage_breakdown`) and as a standalone artifact
    when ``BENCH_AUTOJIT_TRACE_ARTIFACT`` names a path — the "where the
    Table-path tax went" evidence the ROADMAP asks for. Best-of-3 per
    mode: single-trial numbers on shared CI runners catch GC pauses and
    neighbor load (the r05 encdec lesson).
    """
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.engine.flight_recorder import FlightRecorder
    from pathway_tpu.internals import autojit
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    if n_docs is None:
        n_docs = AUTOJIT_DOCS
    n_docs -= n_docs % AUTOJIT_TICK
    n_ticks = n_docs // AUTOJIT_TICK
    embed_kernel = _autojit_payload()

    # the scoring chain: six sync scalar UDFs spanning every class the
    # tier compiles (jit-traceable int/conditional float -> XLA group;
    # compounding-float / math.sqrt / integer-division bodies -> numpy
    # group) — interpreted per row per UDF when auto-jit is off, exactly
    # the per-doc host tax the real framework leg pays around its
    # embedder (parse/split/metadata UDFs)
    import math

    @pw.udf
    def boost(x: int) -> int:
        return x * 3 + 7

    @pw.udf
    def gate(y: float) -> float:
        return y if y < 0.75 else 0.75

    @pw.udf
    def mix(x: int, y: float) -> float:
        return x * 0.0001 + y * 0.5

    @pw.udf
    def norm(y: float) -> float:
        return math.sqrt(y) + 1.0

    @pw.udf
    def damp(y: float) -> float:
        return y * 0.5 + 0.25

    @pw.udf
    def step(x: int) -> int:
        return (x % 7) + (x // 3)

    @pw.udf(deterministic=True)
    def tag(x: int) -> str:
        return f"doc-{x % 97}"

    @pw.udf(batch=True, device=True, deterministic=True, return_type=float)
    def embed(xs):
        ids = np.asarray(xs, np.int64) % _AUTOJIT_VOCAB
        return embed_kernel(ids.astype(np.int32)).tolist()

    rng = np.random.default_rng(3)
    xs = rng.integers(0, 1_000_000, size=n_docs)
    ys = rng.random(size=n_docs)
    rows = [(int(x), float(y), i // AUTOJIT_TICK, 1)
            for i, (x, y) in enumerate(zip(xs, ys))]
    schema = sch.schema_from_types(x=int, y=float)

    def run_framework() -> tuple[float, list, dict, dict]:
        G.clear()
        autojit.reset_stats()
        t = table_from_rows(schema, rows, is_stream=True)
        t1 = t.select(sb=boost(t.x), sg=gate(t.y), sm=mix(t.x, t.y),
                      sn=norm(t.y), sd=damp(t.y), st=step(t.x),
                      tg=tag(t.x))
        t2 = t1.select(emb=embed(t1.sb), tg=t1.tg, sg=t1.sg, sm=t1.sm,
                       sn=t1.sn, sd=t1.sd, st=t1.st)
        runner = GraphRunner()
        cap = runner.capture(t2)
        # first-tick compiles belong in warmup, not the timed window:
        # walk the fused programs' bucket ladders (satellite contract —
        # pw.warmup after building the runner) and prime the embed kernel
        # at the tick shape
        warm = pw.warmup(cache=False)
        embed_kernel(np.zeros(AUTOJIT_TICK, np.int32))
        rec = FlightRecorder()
        rec.enabled = True
        t0 = time.perf_counter()
        runner.run_batch(n_workers=1, recorder=rec)
        dt = time.perf_counter() - t0
        bridge = runner._scheduler.bridge_stats()
        stages = [
            {"op": s["name"], "op_class": s["op_class"],
             "ms": round(s["sum_ms"], 1), "steps": s["count"],
             "rows_in": s["rows_in"]}
            for s in sorted(rec.op_stats(), key=lambda s: -s["sum_ms"])]
        out_rows = [r for _, r, _, d in cap.events if d > 0]
        G.clear()
        meta = {
            "bridge": bridge,
            "warmup_autojit_compiles": sum(
                1 for kind, _ in warm["compiled"] if kind == "autojit"),
            "stats": autojit.autojit_stats(),
        }
        return dt, out_rows, meta, {"stages": stages}

    def run_raw() -> tuple[float, list]:
        t0 = time.perf_counter()
        out = []
        for tk in range(n_ticks):
            lo = tk * AUTOJIT_TICK
            chunk = rows[lo:lo + AUTOJIT_TICK]
            xa = np.fromiter((r[0] for r in chunk), np.int64, len(chunk))
            ya = np.fromiter((r[1] for r in chunk), np.float64, len(chunk))
            sb = xa * 3 + 7
            sg = np.minimum(ya, 0.75)
            sm = xa * 0.0001 + ya * 0.5
            sn = np.sqrt(ya) + 1.0
            sd = ya * 0.5 + 0.25
            st = (xa % 7) + (xa // 3)
            tg = [f"doc-{int(v) % 97}" for v in xa.tolist()]
            emb = embed_kernel((sb % _AUTOJIT_VOCAB).astype(np.int32))
            out.extend(zip(emb.tolist(), tg, sg.tolist(), sm.tolist(),
                           sn.tolist(), sd.tolist(), st.tolist()))
        dt = time.perf_counter() - t0
        return dt, out

    prev = os.environ.get("PATHWAY_AUTO_JIT")
    try:
        # wake the jit once outside every timed window
        embed_kernel(np.zeros(AUTOJIT_TICK, np.int32))
        # INTERLEAVED best-of-3 (the r05 lesson, round 2): the three modes
        # run round-robin so a neighbor-load / GC episode on a shared
        # runner lands on all of them, not on whichever phase it straddles
        # — phase-sequential trials measured ratio swings of ±0.3 on this
        # container with an unchanged binary
        raw_best = on_best = off_best = None
        for _ in range(3):
            trial = run_raw()
            if raw_best is None or trial[0] < raw_best[0]:
                raw_best = trial
            os.environ["PATHWAY_AUTO_JIT"] = "1"
            trial = run_framework()
            if on_best is None or trial[0] < on_best[0]:
                on_best = trial
            os.environ["PATHWAY_AUTO_JIT"] = "0"
            trial = run_framework()
            if off_best is None or trial[0] < off_best[0]:
                off_best = trial
            if prev is None:
                os.environ.pop("PATHWAY_AUTO_JIT", None)
            else:
                os.environ["PATHWAY_AUTO_JIT"] = prev
        raw_dt, raw_out = raw_best
        on_dt, on_rows, on_meta, on_stages = on_best
        off_dt, off_rows, off_meta, off_stages = off_best
    finally:
        if prev is None:
            os.environ.pop("PATHWAY_AUTO_JIT", None)
        else:
            os.environ["PATHWAY_AUTO_JIT"] = prev

    # byte-identity across all three paths is part of the leg's contract:
    # a fast-but-wrong fused tier must fail the bench, not ship a number
    # (sorted: the source's consolidation pass may reorder within a tick)
    assert sorted(on_rows) == sorted(off_rows), \
        "auto-jit changed the framework output"
    assert sorted(on_rows) == sorted(raw_out), \
        "framework output diverged from the raw comparator"

    on_stats = on_meta["stats"]
    out = {
        "autojit_n_docs": n_docs,
        "autojit_raw_docs_per_s": round(n_docs / raw_dt, 1),
        "autojit_framework_docs_per_s": round(n_docs / on_dt, 1),
        "autojit_framework_docs_per_s_nojit": round(n_docs / off_dt, 1),
        "framework_vs_raw_ratio": round(raw_dt / on_dt, 3),
        "framework_vs_raw_ratio_nojit": round(raw_dt / off_dt, 3),
        "autojit_programs": on_stats["programs"],
        "autojit_compiles": on_stats["compiles"],
        "autojit_demotions": on_stats["demotions"],
        "autojit_bucket_count": on_stats["bucket_count"],
        "autojit_device_dispatches": on_stats["device_dispatches"],
        "autojit_vector_dispatches": on_stats["vector_dispatches"],
        "autojit_fallback_batches": on_stats["fallback_batches"],
        "autojit_warmup_compiles": on_meta["warmup_autojit_compiles"],
        "autojit_bridge_overlap_ratio": round(
            on_meta["bridge"]["overlap_ratio"], 3)
        if on_meta["bridge"] else None,
        "autojit_stage_breakdown": {
            "on": on_stages["stages"][:8], "off": off_stages["stages"][:8]},
    }
    trace_path = os.environ.get("BENCH_AUTOJIT_TRACE_ARTIFACT")
    if trace_path:
        from pathway_tpu.engine.flight_recorder import atomic_write_json

        atomic_write_json(trace_path, {
            "leg": "autojit", "n_docs": n_docs,
            "summary": {k: v for k, v in out.items()
                        if k != "autojit_stage_breakdown"},
            "per_stage_ms": {"on": on_stages["stages"],
                             "off": off_stages["stages"]},
        })
    return out


def bench_scaleout() -> dict:
    """Honest multi-worker scale-out leg: the WordCount+join ETL pipeline
    run as ONE process and as FOUR OS processes (SPMD cluster,
    engine/multiproc.py) over both transports, reporting

    * ``etl_scaleout_efficiency`` = (4-process rate / 1-process rate) /
      min(4, cores) — the cores-vs-workers honesty rule from bench_etl: on
      fewer than 4 cores the 4-process figure measures timesharing, so
      the denominator only credits cores that exist and
      ``scaleout_oversubscribed`` flags the run (CI gates ≥ 0.7 only on
      ≥ 4-core runners — tests/scaleout_canary.py);
    * byte-identity: the union of the 4 shards' consolidated outputs must
      equal the 1-process events exactly, per transport;
    * per-transport exchange cost from the live cluster counters (the
      same numbers /metrics exports as pathway_tpu_exchange_*{transport=}).
    """
    import subprocess
    import sys as _sys
    import tempfile

    n_rows = int(os.environ.get("BENCH_SCALEOUT_ROWS", 100_000))
    n_ticks = int(os.environ.get("BENCH_SCALEOUT_TICKS", 20))
    first_port = int(os.environ.get("BENCH_SCALEOUT_PORT", 19600))
    workers = 4
    cores = os.cpu_count() or 1

    tmp = tempfile.mkdtemp(prefix="bench_scaleout_")
    prog = os.path.join(tmp, "scaleout_prog.py")
    with open(prog, "w") as f:
        f.write(_SCALEOUT_PROGRAM)
    base_env = dict(os.environ, JAX_PLATFORMS="cpu",
                    BENCH_SCALEOUT_ROWS=str(n_rows),
                    BENCH_SCALEOUT_TICKS=str(n_ticks))
    base_env.setdefault("PYTHONPATH", os.path.dirname(
        os.path.abspath(__file__)))

    def run_procs(n: int, port: int, transport: str) -> list[dict]:
        handles = []
        for pid in range(n):
            env = dict(base_env, PATHWAY_PROCESSES=str(n),
                       PATHWAY_PROCESS_ID=str(pid), PATHWAY_THREADS="1",
                       PATHWAY_FIRST_PORT=str(port),
                       PATHWAY_RUN_ID=f"scaleout-{transport}",
                       PATHWAY_EXCHANGE_TRANSPORT=transport)
            out_path = os.path.join(tmp, f"out_{transport}_{n}_{pid}")
            handles.append((out_path, subprocess.Popen(
                [_sys.executable, prog, out_path], env=env,
                stderr=subprocess.PIPE, text=True)))
        docs = []
        try:
            for out_path, h in handles:
                _, err = h.communicate(timeout=600)
                if h.returncode != 0:
                    raise RuntimeError(
                        f"scaleout child failed (rc={h.returncode}): "
                        f"{err[-500:]}")
                with open(out_path) as f:
                    docs.append(json.load(f))
        except BaseException:
            # one child failing/timing out must not orphan its siblings:
            # bench's main() absorbs this error and runs more legs, and a
            # leaked 4-process cluster spins in exchange retries (recv
            # timeout 300 s), distorting every later timing in the artifact
            # and squatting on the ports for the next transport's run.
            for _, h in handles:
                if h.poll() is None:
                    h.kill()
            for _, h in handles:
                try:
                    h.communicate(timeout=10)
                except Exception:
                    pass
            raise
        return docs

    [single] = run_procs(1, first_port, "tcp")
    rate_1p = n_rows / single["dt_s"]
    out: dict = {
        "scaleout_rows": n_rows,
        "scaleout_ticks": n_ticks,
        "scaleout_workers": workers,
        "scaleout_n_cores": cores,
        "scaleout_oversubscribed": cores < workers,
        "scaleout_rows_per_s_1p": round(rate_1p, 0),
        "scaleout_rounds_per_tick": single["rounds_per_tick"],
    }
    expect = sorted(map(tuple, single["events"]))
    best_rate, best_transport = 0.0, None
    for transport in ("shm", "tcp"):
        docs = run_procs(workers, first_port + 20
                         + (0 if transport == "shm" else 20), transport)
        # collective run: the slowest process bounds the wall-clock
        rate = n_rows / max(d["dt_s"] for d in docs)
        merged = sorted(tuple(e) for d in docs for e in d["events"])
        identical = merged == expect
        used = {t for d in docs for t in d["transports"]}
        st = docs[0]["stats"]
        t_st = docs[0]["by_transport"][transport]
        enc_us = (t_st["encode_s"] * 1e6 / t_st["rows_out"]
                  if t_st["rows_out"] else 0.0)
        dec_us = (t_st["decode_s"] * 1e6 / t_st["rows_in"]
                  if t_st["rows_in"] else 0.0)
        out.update({
            f"scaleout_rows_per_s_4p_{transport}": round(rate, 0),
            f"scaleout_identical_{transport}": identical,
            f"scaleout_transport_used_{transport}": sorted(used),
            f"scaleout_exchange_encode_us_per_row_{transport}": round(
                enc_us, 3),
            f"scaleout_exchange_decode_us_per_row_{transport}": round(
                dec_us, 3),
            f"scaleout_exchange_rounds_{transport}": st["rounds"],
        })
        if transport == "shm":
            out["scaleout_shm_slab_bytes"] = (st["shm_bytes_out"]
                                              + st["shm_bytes_in"])
        if identical and rate > best_rate:
            best_rate, best_transport = rate, transport
    if best_transport is not None:
        out["etl_scaleout_efficiency"] = round(
            (best_rate / rate_1p) / min(workers, cores), 3)
        out["scaleout_best_transport"] = best_transport
    return out


def bench_paging() -> dict:
    """Paged-store leg (CPU-runnable, also meaningful on device): the two
    acceptance numbers of the paged HBM vector store.

    1. **Ingest stall during online growth**: identical chunked ingest
       into the paged store and the contiguous slab, growth forced
       mid-stream, each chunk flushed+drained so its wall time includes
       its device work. The slab pays a stop-the-world full re-upload on
       the first flush after every growth; the paged store only
       establishes a fresh extent — ``paging_grow_stall_ms_paged`` vs
       ``_slab`` is that difference, measured.
    2. **Warmup compile count under ragged batching**: the encoder's
       width-bucket zoo (~18 shapes) vs the ragged sequence-count buckets
       ``pw.warmup`` actually compiles (≤ 6).
    """
    import pathway_tpu as pw
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.ops.knn import (BruteForceKnnIndex,
                                     DeviceEmbeddingKnnIndex, KnnMetric)
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    dim = int(os.environ.get("BENCH_PAGING_DIM", 256))
    chunk = int(os.environ.get("BENCH_PAGING_CHUNK", 4096))
    total = int(os.environ.get("BENCH_PAGING_ROWS", 16 * 4096))
    rng = np.random.default_rng(0)
    vecs = (rng.random((total, dim), np.float32) * 2.0 - 1.0)

    def run_mode(paged: bool) -> dict:
        index = BruteForceKnnIndex(dim, reserved_space=2 * chunk,
                                   metric=KnnMetric.COS, paged=paged)
        chunk_ms: list[float] = []
        grow_chunks: list[float] = []
        for base in range(0, total, chunk):
            m = min(chunk, total - base)
            keys = [Pointer(base + i) for i in range(m)]
            cap_before = index.capacity
            t0 = time.perf_counter()
            index.add_batch(keys, vecs[base:base + m])
            index.flush_device()
            index.drain()
            ms = (time.perf_counter() - t0) * 1e3
            chunk_ms.append(ms)
            if index.capacity > cap_before:
                grow_chunks.append(ms)
        res = index.search([(Pointer(10**9), vecs[7], 5, None)])
        out = {
            "ingest_p50_ms": round(float(np.percentile(chunk_ms, 50)), 2),
            "ingest_p99_ms": round(float(np.percentile(chunk_ms, 99)), 2),
            "grow_stall_ms": round(max(grow_chunks), 2) if grow_chunks
            else None,
            "grow_events": len(grow_chunks),
            # rows written to device / rows ingested: the slab re-ships
            # every occupied slot after each growth (stop-the-world
            # re-upload); the paged store writes each row ONCE. This is
            # the environment-independent form of the growth stall (on
            # CPU, wall-ms mostly measures XLA compile churn instead)
            "upload_amplification": round(
                index.upload_rows_total / total, 3),
        }
        return out, res

    paged, res_p = run_mode(True)
    slab, res_s = run_mode(False)
    out = {"paging_rows": total, "paging_dim": dim,
           "paging_chunk": chunk,
           "paging_identical_topk": res_p == res_s}
    for k, v in paged.items():
        out[f"paging_{k}_paged"] = v
    for k, v in slab.items():
        out[f"paging_{k}_slab"] = v

    # warmup compile count: ragged buckets vs the width-bucket zoo (tiny
    # encoder shape — the COUNT is the metric, the model size is not)
    cfg = EncoderConfig.tiny(max_len=512)
    emb = JaxEncoderEmbedder(config=cfg, ragged=True, max_len=512)
    idx = DeviceEmbeddingKnnIndex(
        emb, BruteForceKnnIndex(cfg.hidden, metric=KnnMetric.COS,
                                paged=True))
    t0 = time.perf_counter()
    warm = pw.warmup(emb, index=idx, cache=False)
    out["paging_warmup_compiles_ragged"] = len(warm["compiled"])
    out["paging_warmup_seconds_ragged"] = round(
        time.perf_counter() - t0, 2)
    out["paging_warmup_bucket_shapes"] = len(emb.bucket_widths())
    return out


def _dispatch_floor_ms() -> float:
    """Per-dispatch host↔device overhead (huge on a tunneled dev chip,
    ~0.1 ms on production hardware) — measured so the reported e2e numbers
    are interpretable."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def trivial(x):
        return x + 1.0

    x = jnp.zeros((8, 8), jnp.float32)
    np.asarray(trivial(x))
    lat = []
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(trivial(x))
        lat.append((time.perf_counter() - t0) * 1e3)
    return float(np.percentile(lat, 50))


def _bench_knn_int8(n, gen, chunk, queries, bf16_top) -> dict:
    """int8-slab leg (half of bf16's bytes): p50 at the same scale, plus
    an overlap@10 probe vs the bf16 results over IDENTICAL vectors (the
    generator chunks are re-created from the same PRNG keys)."""
    import gc

    import jax

    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    gc.collect()
    index = BruteForceKnnIndex(KNN_DIM, reserved_space=n,
                               metric=KnnMetric.COS, dtype="int8")
    for ci, base in enumerate(range(0, n, chunk)):
        m = min(chunk, n - base)
        vecs = gen(jax.random.PRNGKey(ci))
        index.add_batch_device(
            [Pointer(base + i) for i in range(m)], vecs[:m])
    res = index.search([(Pointer(10**9 + i), queries[i], 10, None)
                        for i in range(8)])
    overlap = float(np.mean(
        [len(set(k for k, _ in res[i]) & set(bf16_top[i])) / 10.0
         for i in range(8)]))
    p50 = index.latency_probe(batch_size=1, k=10, reps=64)
    b64 = index.latency_probe(batch_size=64, k=10, reps=16)
    del index
    gc.collect()
    return {
        "knn_int8_p50_ms": round(p50, 2),
        "knn_int8_batch64_ms": round(b64, 2),
        "knn_int8_overlap10_vs_bf16": round(overlap, 3),
    }


def bench_durability() -> dict:
    """Checkpoint cadence vs pipeline depth (resolved-prefix commit
    watermark, engine/device_bridge.py + engine/persistence.py).

    Runs one paced streaming graph — python connector → device-leg batch
    UDF (a fixed per-leg device stand-in delay on CPU; the mechanics
    under test are the bridge/commit interactions, not kernel speed) →
    groupby — three ways: inflight=4 with persistence ON, inflight=4
    with persistence OFF, inflight=1 with persistence ON. Reports the
    bridge overlap ratio of each plus ticks-per-commit and watermark lag,
    so the acceptance bar "persistence-on overlap within 10% of
    persistence-off at inflight=4" is a captured number, not a claim.
    """
    import tempfile

    import pathway_tpu as pw
    from pathway_tpu.engine.streaming import StreamingRuntime
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    n_rows = int(os.environ.get("BENCH_DURABILITY_ROWS", 40))
    leg_ms = float(os.environ.get("BENCH_DURABILITY_LEG_MS", 20.0))

    def run_once(inflight: int, persist_dir: str | None) -> dict:
        os.environ["PATHWAY_DEVICE_INFLIGHT"] = str(inflight)
        G.clear()

        @pw.udf(batch=True, device=True, deterministic=True,
                return_type=int)
        def dev_score(qty: list) -> list:
            time.sleep(leg_ms / 1e3)
            return [int(q) * 2 for q in qty]

        class _Feed(pw.io.python.ConnectorSubject):
            def run(self):
                for i in range(n_rows):
                    time.sleep(0.004)
                    self.next(item=f"i{i % 5}", qty=1 + i % 3)

        t = pw.io.python.read(
            _Feed(), schema=pw.schema_from_types(item=str, qty=int),
            autocommit_duration_ms=10, persistent_id="bench-durability")
        t = t.select(item=t.item, score=dev_score(t.qty))
        agg = t.groupby(t.item).reduce(item=t.item,
                                       s=pw.reducers.sum(t.score))
        pw.io.subscribe(agg, lambda *a, **k: None)
        cfg = None
        if persist_dir is not None:
            cfg = pw.persistence.Config.simple_config(
                pw.persistence.Backend.filesystem(persist_dir))
        runner = GraphRunner()
        for binder in G.output_binders:
            binder(runner)
        rt = StreamingRuntime(runner, persistence_config=cfg)
        t0 = time.perf_counter()
        rt.run()
        wall_s = time.perf_counter() - t0
        bridge = rt.scheduler.bridge_stats() or {}
        pstats = rt.persistence.stats() if rt.persistence else {}
        G.clear()
        return {"wall_s": wall_s, "bridge": bridge, "pstats": pstats}

    out: dict = {}
    prior_inflight = os.environ.get("PATHWAY_DEVICE_INFLIGHT")
    try:
        with tempfile.TemporaryDirectory() as td:
            p4 = run_once(4, os.path.join(td, "p4"))
            nop4 = run_once(4, None)
            p1 = run_once(1, os.path.join(td, "p1"))
    finally:
        # later legs (and the device-phase child env) must see the
        # caller's pipelining depth, not this leg's last override
        if prior_inflight is None:
            os.environ.pop("PATHWAY_DEVICE_INFLIGHT", None)
        else:
            os.environ["PATHWAY_DEVICE_INFLIGHT"] = prior_inflight
    out["durability_overlap_inflight4_persist"] = round(
        p4["bridge"].get("overlap_ratio", 0.0), 3)
    out["durability_overlap_inflight4_nopersist"] = round(
        nop4["bridge"].get("overlap_ratio", 0.0), 3)
    out["durability_bridge_max_depth_persist"] = \
        p4["bridge"].get("max_depth", 0)
    for tag, leg in (("inflight4", p4), ("inflight1", p1)):
        ps = leg["pstats"]
        commits = max(1, ps.get("commits_with_data", 0))
        out[f"durability_commits_{tag}"] = ps.get("commits_with_data", 0)
        out[f"durability_ticks_per_commit_{tag}"] = round(
            ps.get("watermark", 0) / commits, 2)
        out[f"durability_wall_s_{tag}"] = round(leg["wall_s"], 3)
    out["durability_watermark_lag_ticks"] = p4["pstats"].get(
        "lag_ticks", 0)
    return out


def bench_recovery() -> dict:
    """Bounded-time crash recovery (PR 10): restart wall-clock vs history
    size, WAL-only vs snapshot+suffix (engine/persistence.py operator-state
    snapshots + compaction).

    For each history size H: synthesize a WAL of H rows directly through
    the durable log API (the on-disk format a real run writes), then
    measure a restart three ways — (1) full-WAL replay, (2) one more
    replay with snapshots ON (its teardown writes the generation and
    compacts), (3) the snapshot-restored restart. WAL-only restart grows
    linearly with H; the snapshot restart must stay ~flat: the acceptance
    bar is restart(100k) <= 2x restart(1k) with snapshots on, reported as
    ``recovery_snapshot_ratio_maxmin``.
    """
    import tempfile

    import pathway_tpu as pw
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.internals.parse_graph import G

    sizes = [int(s) for s in os.environ.get(
        "BENCH_RECOVERY_ROWS", "1000,10000,100000").split(",")]
    chunk = 500  # rows per WAL record (one commit's worth)

    class _Closed(pw.io.python.ConnectorSubject):
        def run(self):
            return  # nothing live: the restart is pure recovery

    def run_restart(pdir: str) -> float:
        G.clear()
        t = pw.io.python.read(
            _Closed(), schema=pw.schema_from_types(word=str),
            autocommit_duration_ms=10, persistent_id="bench-recovery")
        counts = t.groupby(t.word).reduce(word=t.word,
                                          c=pw.reducers.count())
        pw.io.subscribe(counts, lambda *a, **k: None)
        cfg = pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(pdir))
        t0 = time.perf_counter()
        pw.run(persistence_config=cfg)
        wall = time.perf_counter() - t0
        G.clear()
        return wall

    out: dict = {}
    prior = {k: os.environ.get(k) for k in
             ("PATHWAY_SNAPSHOT_EVERY_TICKS", "PATHWAY_DEVICE_INFLIGHT")}
    os.environ["PATHWAY_DEVICE_INFLIGHT"] = "1"
    snap_restarts: dict[int, float] = {}
    try:
        for n in sizes:
            with tempfile.TemporaryDirectory() as td:
                pdir = os.path.join(td, "p")
                driver = PersistenceDriver(
                    pw.persistence.Config.simple_config(
                        pw.persistence.Backend.filesystem(pdir)))
                log = driver._log_for("bench-recovery")
                # fixed 1000-word vocabulary at every history size: the
                # aggregation STATE stays constant while the input log
                # grows — exactly the regime where an input-WAL restart
                # is O(stream age) and a state snapshot is O(state)
                tick = 0
                for base in range(0, n, chunk):
                    tick += 1
                    log.append(tick, [
                        (Pointer(i), (f"w{i % 1000}",), 1, None)
                        for i in range(base, min(base + chunk, n))])
                log.close()
                os.environ.pop("PATHWAY_SNAPSHOT_EVERY_TICKS", None)
                # min of two: first-run import/compile noise must not
                # masquerade as replay cost (both restarts are pure
                # recovery over the identical root)
                wal_s = min(run_restart(pdir), run_restart(pdir))
                # snapshot-prep replay: teardown writes the generation
                # covering the whole history and compacts the WAL
                os.environ["PATHWAY_SNAPSHOT_EVERY_TICKS"] = "1000000000"
                run_restart(pdir)
                snap_s = min(run_restart(pdir), run_restart(pdir))
                out[f"recovery_walonly_restart_s_{n}"] = round(wal_s, 3)
                out[f"recovery_snapshot_restart_s_{n}"] = round(snap_s, 3)
                snap_restarts[n] = snap_s
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if snap_restarts:
        lo, hi = min(sizes), max(sizes)
        out["recovery_snapshot_ratio_maxmin"] = round(
            snap_restarts[hi] / max(snap_restarts[lo], 1e-9), 3)
    return out


_REPLICA_PROGRAM = """
# One member of the replica-fleet bench/canary (bench_replica): the
# SAME KNN-serving program run as the PRIMARY (ingests the seeded vector
# feed under persistence, then trickles so staleness stays a live
# number) or as a READ REPLICA (PATHWAY_REPLICA_OF, hydrates + tails;
# registers with the router through PATHWAY_ROUTER_CONTROL). A fixed
# per-query sleep in the post-KNN UDF stands in for per-query device
# cost (rerank/fetch): the router's load spreading is only measurable
# if a query COSTS something, and a sleep costs wall-clock without
# needing a core — so the 1-vs-2-replica p95 drop is honest even on a
# 1-core runner.
import json, os, sys, threading, time
import numpy as np
import pathway_tpu as pw
from pathway_tpu.engine import streaming as _streaming
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from pathway_tpu.io.http import PathwayWebserver, rest_connector
from pathway_tpu.stdlib.indexing import (
    default_brute_force_knn_document_index)

DIM = 16
ROLE = os.environ["REPLICA_BENCH_ROLE"]
ROOT = os.environ["REPLICA_BENCH_ROOT"]
N = int(os.environ.get("REPLICA_BENCH_VECS", "256"))
COST_MS = float(os.environ.get("REPLICA_BENCH_QUERY_COST_MS", "4"))
# trickle cadence: how often a fresh vector lands after the seed load.
# The semantic-cache leg stretches this (ingest stays LIVE, but the
# index-version watermark holds long enough for router fills to commit
# — a fill is discarded when the watermark moves mid-forward)
TRICKLE_S = float(os.environ.get("REPLICA_BENCH_TRICKLE_S", "0.5"))
READY = os.environ.get("REPLICA_BENCH_READY_FILE")
# fleet-observability mode (tests/fleet_trace_canary.py): each process
# runs its monitoring HTTP server (ephemeral port, announced over the
# control-channel heartbeat) so the router can scrape /metrics and
# /trace?format=chrome for the /fleet/* surfaces
HTTP = os.environ.get("REPLICA_BENCH_HTTP") == "1"
# write-path mode (tests/failover_canary.py): a durable-ack /w route on
# every member — the primary serves it, replicas tail its WAL so a
# promoted replica owns the full write history
WRITES = os.environ.get("REPLICA_BENCH_WRITES") == "1"
# crash-mid-promotion mode: die (rc 3) INSIDE the promotion, after the
# epoch bump but before connector readers start — the router must
# re-elect a survivor
if os.environ.get("REPLICA_BENCH_PROMOTE_CRASH") == "1":
    from pathway_tpu.testing import faults as _faults
    _faults.arm_point("replica.promote.crash",
                      lambda _p, _c: os._exit(3))


class Subject(pw.io.python.ConnectorSubject):
    def run(self):
        rng = np.random.default_rng(11)
        for i in range(N):
            self.next(v=rng.random(DIM, np.float32) * 2 - 1)
            if i % 32 == 31 and not self._session.sleep(0.05):
                return
        while True:  # trickle: keep the WAL (and staleness) live
            if not self._session.sleep(TRICKLE_S):
                return
            self.next(v=rng.random(DIM, np.float32) * 2 - 1)


ws = PathwayWebserver(host="127.0.0.1", port=0)
data = pw.io.python.read(
    Subject(), schema=sch.schema_from_types(v=np.ndarray),
    autocommit_duration_ms=25, name="vecs", persistent_id="vecs")
index = default_brute_force_knn_document_index(
    data.v, data, dimensions=DIM, reserved_space=4096)
qschema = sch.schema_from_types(vec=dt.ANY, k=int)
queries, writer = rest_connector(
    webserver=ws, route="/q", schema=qschema, methods=("POST",),
    delete_completed_queries=True, autocommit_duration_ms=10)
qv = queries.select(
    qv=pw.apply(lambda v: np.asarray(v, dtype=np.float32), queries.vec),
    k=queries.k)
res = index.query_as_of_now(qv.qv, number_of_matches=qv.k)


def _ids(ids):
    time.sleep(COST_MS / 1e3)  # the per-query device-cost stand-in
    return [str(i) for i in ids]


writer(res.select(
    ids=pw.apply(_ids, res._pw_index_reply_id),
    scores=pw.apply(lambda ds: [float(d) for d in ds],
                    res._pw_index_reply_score)))

if WRITES:
    # the write path: durable-ack ingestion with an IDEMPOTENT aggregate
    # (key -> max value), so a client retrying an un-acked POST after
    # failover cannot corrupt state — the 200 means the row is fsynced
    # in the primary root's WAL
    wrows, wack = rest_connector(
        webserver=ws, route="/w",
        schema=sch.schema_from_types(wkey=str, wval=int),
        methods=("POST",), persistent_id="writes",
        autocommit_duration_ms=10, durable_ack=True)
    agg = wrows.groupby(wrows.wkey).reduce(
        wkey=wrows.wkey, wval=pw.reducers.max(wrows.wval))
    pw.io.subscribe(agg, lambda *a, **k: None)
    wack(wrows.select(ok=wrows.wval))


def _announce():
    while not ws._started.is_set():
        time.sleep(0.02)
    def write(doc):
        if not READY:
            return
        with open(READY + ".tmp", "w") as f:
            json.dump(doc, f)
        os.replace(READY + ".tmp", READY)
    write({"port": ws.port, "pid": os.getpid(), "seeded": False})
    if ROLE == "primary":
        while True:  # flip `seeded` once the initial N vectors are durable
            rts = list(_streaming._ACTIVE_RUNTIMES)
            if rts and rts[0].persistence is not None \\
                    and rts[0].persistence.entries_committed >= N:
                write({"port": ws.port, "pid": os.getpid(),
                       "seeded": True})
                return
            time.sleep(0.05)


threading.Thread(target=_announce, daemon=True).start()

if ROLE == "primary":
    pw.run(persistence_config=pw.persistence.Config(
        backend=pw.persistence.Backend.filesystem(ROOT)),
        with_http_server=HTTP)
else:
    pw.run(replica_of=ROOT, with_http_server=HTTP)
"""


class _ReplicaFleet:
    """Multi-process replica-fleet harness shared by bench_replica and
    tests/replica_canary.py: an in-process QueryRouter fronting a primary
    + N read replicas, each a real OS process running _REPLICA_PROGRAM.
    The parent generates closed-loop query load against the router's
    front port and measures end-to-end latency — the numbers a client of
    the fleet would see."""

    def __init__(self, tmp: str, *, vecs: int = 256,
                 query_cost_ms: float = 25.0,
                 observability: bool = False, writes: bool = False):
        import sys as _sys

        self.tmp = tmp
        # fleet-observability mode (tests/fleet_trace_canary.py): every
        # member runs its monitoring HTTP server on an ephemeral port
        # with the flight recorder on, and the PRIMARY also registers
        # with the router (read-serving last resort) so /fleet/* covers
        # the whole fleet
        self.observability = observability
        self.root = os.path.join(tmp, "primary-root")
        self.prog = os.path.join(tmp, "replica_prog.py")
        with open(self.prog, "w") as f:
            f.write(_REPLICA_PROGRAM)
        self._py = _sys.executable
        self.base_env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            PATHWAY_RUN_ID="replica-bench",
            REPLICA_BENCH_ROOT=self.root,
            REPLICA_BENCH_VECS=str(vecs),
            REPLICA_BENCH_QUERY_COST_MS=str(query_cost_ms))
        self.base_env.setdefault("PYTHONPATH", os.path.dirname(
            os.path.abspath(__file__)))
        # children must not inherit replica/monitoring config from the
        # parent's environment
        for k in ("PATHWAY_REPLICA_OF", "PATHWAY_ROUTER_CONTROL",
                  "PATHWAY_REPLICA_ID", "PATHWAY_SNAPSHOT_EVERY_TICKS",
                  "PATHWAY_MONITORING_HTTP_PORT", "PATHWAY_PROCESSES"):
            self.base_env.pop(k, None)
        if observability:
            self.base_env.update(
                REPLICA_BENCH_HTTP="1",
                PATHWAY_MONITORING_HTTP_PORT="0",  # ephemeral, in the hb
                PATHWAY_FLIGHT_RECORDER="1")
        if writes:
            self.base_env["REPLICA_BENCH_WRITES"] = "1"
        self.vecs = vecs
        self.router = None
        self.procs: dict[str, object] = {}  # name -> Popen

    # -- lifecycle ---------------------------------------------------------
    def start_router(self, *, write_paths=None,
                     election_timeout_ms: int | None = None):
        from pathway_tpu.engine.router import QueryRouter

        prior = os.environ.get("PATHWAY_RUN_ID")
        os.environ["PATHWAY_RUN_ID"] = "replica-bench"  # shared authkey
        prior_et = os.environ.get("PATHWAY_ROUTER_ELECTION_TIMEOUT_MS")
        if election_timeout_ms is not None:
            os.environ["PATHWAY_ROUTER_ELECTION_TIMEOUT_MS"] = str(
                election_timeout_ms)
        try:
            self.router = QueryRouter(port=0, control_port=0,
                                      write_paths=write_paths)
            self.router.start()
        finally:
            if election_timeout_ms is not None:
                if prior_et is None:
                    os.environ.pop("PATHWAY_ROUTER_ELECTION_TIMEOUT_MS",
                                   None)
                else:
                    os.environ["PATHWAY_ROUTER_ELECTION_TIMEOUT_MS"] = \
                        prior_et
            if prior is None:
                os.environ.pop("PATHWAY_RUN_ID", None)
            else:
                os.environ["PATHWAY_RUN_ID"] = prior
        return self.router

    def _spawn(self, name: str, env: dict):
        import subprocess

        err = open(os.path.join(self.tmp, f"{name}.stderr"), "w")
        h = subprocess.Popen([self._py, self.prog], env=env,
                             stderr=err, stdout=subprocess.DEVNULL)
        h._err_file = err  # noqa: SLF001 — closed in stop()
        self.procs[name] = h
        return h

    def _check_alive(self, name: str) -> None:
        h = self.procs[name]
        if h.poll() is not None:
            with open(os.path.join(self.tmp, f"{name}.stderr")) as f:
                tail = f.read()[-800:]
            raise RuntimeError(
                f"fleet member {name} died (rc={h.returncode}): {tail}")

    def start_primary(self, *, snapshot_ticks: int = 4,
                      timeout_s: float = 120.0, register: bool = False):
        ready = os.path.join(self.tmp, "primary.ready")
        env = dict(self.base_env, REPLICA_BENCH_ROLE="primary",
                   REPLICA_BENCH_READY_FILE=ready,
                   PATHWAY_SNAPSHOT_EVERY_TICKS=str(snapshot_ticks))
        if register and self.router is not None:
            # failover mode: the primary joins the control plane so the
            # router can detect its death and run an election
            env.update(PATHWAY_REPLICA_ID="primary",
                       PATHWAY_ROUTER_CONTROL=(
                           f"127.0.0.1:{self.router.control_port}"))
        if self.observability and self.router is not None:
            # the primary registers too (role "primary", routed only as
            # a last resort) so /fleet/metrics//fleet/trace cover it
            env.update(PATHWAY_REPLICA_ID="primary",
                       PATHWAY_ROUTER_CONTROL=(
                           f"127.0.0.1:{self.router.control_port}"))
        self._spawn("primary", env)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self._check_alive("primary")
            if os.path.exists(ready):
                with open(ready) as f:
                    doc = json.load(f)
                if doc.get("seeded"):
                    return doc
            time.sleep(0.1)
        raise TimeoutError("primary never finished seeding its WAL")

    def start_replica(self, rid: str, *, max_staleness: int = 4,
                      timeout_s: float = 120.0,
                      promote_crash: bool = False):
        env = dict(self.base_env, REPLICA_BENCH_ROLE="replica",
                   PATHWAY_REPLICA_OF=self.root, PATHWAY_REPLICA_ID=rid,
                   PATHWAY_ROUTER_CONTROL=(
                       f"127.0.0.1:{self.router.control_port}"))
        if promote_crash:
            env["REPLICA_BENCH_PROMOTE_CRASH"] = "1"
        self._spawn(rid, env)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self._check_alive(rid)
            for ep in self.router.endpoints():
                if ep.replica_id == rid and ep.port \
                        and ep.applied_tick > 0 \
                        and ep.staleness_ticks <= max_staleness:
                    self._warm(ep)
                    return ep
            time.sleep(0.05)
        raise TimeoutError(f"replica {rid} never caught up / registered")

    def _warm(self, ep, n: int = 3) -> None:
        """Warm a fresh replica DIRECTLY (bypassing the router) before it
        takes fleet traffic: its first queries pay the one-off KNN
        compile, and a measurement window that includes them measures
        warmup, not serving."""
        import http.client

        body = json.dumps({"vec": [0.1] * 16, "k": 3}).encode()
        for _ in range(n):
            conn = http.client.HTTPConnection(ep.host, ep.port,
                                              timeout=60)
            try:
                conn.request("POST", "/q", body=body,
                             headers={"Content-Type": "application/json"})
                conn.getresponse().read()
            finally:
                conn.close()

    def kill_replica(self, rid: str) -> None:
        self.procs[rid].kill()  # SIGKILL: death, not a graceful drain

    def sigstop(self, name: str) -> None:
        """Freeze a member: its sockets stay open but it goes silent —
        the router's staleness detector (not EOF) must declare it."""
        import signal

        os.kill(self.procs[name].pid, signal.SIGSTOP)

    def sigcont(self, name: str) -> None:
        import signal

        os.kill(self.procs[name].pid, signal.SIGCONT)

    def wait_promoted(self, n: int = 1, timeout_s: float = 120.0) -> str:
        """Wait until the router has completed ``n`` promotions; returns
        the promoted member's id."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.router.promotions_total >= n \
                    and self.router._write_primary_id is not None:
                return self.router._write_primary_id
            time.sleep(0.05)
        raise TimeoutError(
            f"router never completed promotion #{n} "
            f"(promotions={self.router.promotions_total}, "
            f"election={self.router._election})")

    def stderr_text(self, name: str) -> str:
        with open(os.path.join(self.tmp, f"{name}.stderr")) as f:
            return f.read()

    def wait_deregistered(self, rid: str, timeout_s: float = 30.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(e.replica_id != rid for e in self.router.endpoints()):
                return
            time.sleep(0.05)
        raise TimeoutError(f"router never dropped dead replica {rid}")

    # -- load --------------------------------------------------------------
    def run_load(self, seconds: float, *, clients: int = 8,
                 warmup_s: float = 1.0,
                 kill_at_s: float | None = None,
                 kill_rid: str | None = None) -> dict:
        """Closed-loop load from ``clients`` threads against the router
        front door for ``seconds``; optionally SIGKILL ``kill_rid`` at
        ``kill_at_s`` into the window. Returns latency quantiles over
        the post-warmup samples and the FULL-window failure count (a
        lost query is a lost query, warm or not)."""
        import http.client
        import threading as _threading

        body = json.dumps({"vec": [0.1] * 16, "k": 3}).encode()
        samples: list[tuple[float, float, bool]] = []
        lock = _threading.Lock()
        stop_at = time.monotonic() + seconds

        def client():
            while time.monotonic() < stop_at:
                t0 = time.monotonic()
                ok = False
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", self.router.port, timeout=30)
                    try:
                        conn.request(
                            "POST", "/q", body=body,
                            headers={"Content-Type": "application/json"})
                        resp = conn.getresponse()
                        resp.read()
                        ok = resp.status == 200
                    finally:
                        conn.close()
                except OSError:
                    ok = False
                with lock:
                    samples.append(
                        (t0, (time.monotonic() - t0) * 1e3, ok))

        threads = [_threading.Thread(target=client, daemon=True)
                   for _ in range(clients)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        if kill_at_s is not None and kill_rid is not None:
            time.sleep(kill_at_s)
            self.kill_replica(kill_rid)
        for t in threads:
            t.join(timeout=seconds + 60)
        lost = sum(1 for _t, _ms, ok in samples if not ok)
        lat = sorted(ms for t0, ms, ok in samples
                     if ok and t0 >= t_start + warmup_s)
        out = {"queries": len(samples), "lost": lost}
        if lat:
            out["p50_ms"] = round(float(np.percentile(lat, 50)), 3)
            out["p95_ms"] = round(float(np.percentile(lat, 95)), 3)
        return out

    def stop(self) -> None:
        for name, h in self.procs.items():
            if h.poll() is None:
                h.kill()
        for name, h in self.procs.items():
            try:
                h.wait(timeout=10)
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
            err = getattr(h, "_err_file", None)
            if err is not None:
                err.close()
        if self.router is not None:
            self.router.stop()


def _bench_replica_ready_sweep() -> dict:
    """Hydration wall-clock vs history size: for each history H,
    synthesize a WAL of H rows, then measure replica time-to-ready (start
    -> applied tick == primary watermark) twice — WAL-only (tail replay,
    O(stream age)) and snapshot-hydrated (PR-10 restore + empty suffix,
    O(state)). The snapshot path must stay ~flat across histories."""
    import tempfile
    import threading as _threading

    import pathway_tpu as pw
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.internals.parse_graph import G

    sizes = [int(s) for s in os.environ.get(
        "BENCH_REPLICA_ROWS", "1000,10000,50000").split(",")]
    chunk = 500

    class _Closed(pw.io.python.ConnectorSubject):
        def run(self):
            return

    def build():
        G.clear()
        t = pw.io.python.read(
            _Closed(), schema=pw.schema_from_types(word=str),
            autocommit_duration_ms=10, persistent_id="bench-replica")
        counts = t.groupby(t.word).reduce(word=t.word,
                                          c=pw.reducers.count())
        pw.io.subscribe(counts, lambda *a, **k: None)

    def replica_ready_s(pdir: str, target_tick: int) -> tuple[float, dict]:
        build()
        errs: list[BaseException] = []

        def _r():
            try:
                pw.run(replica_of=pdir)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        t0 = time.perf_counter()
        th = _threading.Thread(target=_r, daemon=True)
        th.start()
        ready = None
        deadline = time.monotonic() + 300
        stats = {}
        while time.monotonic() < deadline:
            if errs:
                raise RuntimeError(f"replica run failed: {errs[0]!r}")
            for rt in list(_streaming._ACTIVE_RUNTIMES):
                if rt.replica is not None \
                        and rt.replica.applied_tick >= target_tick:
                    ready = time.perf_counter() - t0
                    stats = rt.replica.stats()
            if ready is not None:
                break
            time.sleep(0.02)
        _streaming.stop_all()
        th.join(timeout=60)
        G.clear()
        if ready is None:
            raise TimeoutError(
                f"replica never reached tick {target_tick} over {pdir}")
        return ready, stats

    out: dict = {}
    prior = os.environ.get("PATHWAY_SNAPSHOT_EVERY_TICKS")
    snap_ready: dict[int, float] = {}
    try:
        for n in sizes:
            with tempfile.TemporaryDirectory() as td:
                pdir = os.path.join(td, "p")
                driver = PersistenceDriver(
                    pw.persistence.Config.simple_config(
                        pw.persistence.Backend.filesystem(pdir)))
                log = driver._log_for("bench-replica")
                tick = 0
                for base in range(0, n, chunk):
                    tick += 1
                    log.append(tick, [
                        (Pointer(i), (f"w{i % 1000}",), 1, None)
                        for i in range(base, min(base + chunk, n))])
                log.close()
                os.environ.pop("PATHWAY_SNAPSHOT_EVERY_TICKS", None)
                # min of two: first-run import/compile noise must not
                # masquerade as tail-replay cost (same rule as
                # bench_recovery's restarts)
                wal_s = min(replica_ready_s(pdir, tick)[0],
                            replica_ready_s(pdir, tick)[0])
                # snapshot prep: one primary restart with snapshots ON —
                # its teardown writes the generation and compacts, so the
                # next replica hydrates O(state) with an empty suffix
                os.environ["PATHWAY_SNAPSHOT_EVERY_TICKS"] = "1000000000"
                build()
                pw.run(persistence_config=pw.persistence.Config
                       .simple_config(
                           pw.persistence.Backend.filesystem(pdir)))
                G.clear()
                snap_s, st = min(replica_ready_s(pdir, tick),
                                 replica_ready_s(pdir, tick),
                                 key=lambda r: r[0])
                out[f"replica_ready_walonly_s_{n}"] = round(wal_s, 3)
                out[f"replica_ready_snapshot_s_{n}"] = round(snap_s, 3)
                out[f"replica_hydrate_s_{n}"] = (
                    None if st.get("hydrate_wall_s") is None
                    else round(st["hydrate_wall_s"], 3))
                snap_ready[n] = snap_s
    finally:
        if prior is None:
            os.environ.pop("PATHWAY_SNAPSHOT_EVERY_TICKS", None)
        else:
            os.environ["PATHWAY_SNAPSHOT_EVERY_TICKS"] = prior
    if snap_ready:
        lo, hi = min(sizes), max(sizes)
        out["replica_snapshot_ready_ratio_maxmin"] = round(
            snap_ready[hi] / max(snap_ready[lo], 1e-9), 3)
    return out


def bench_replica() -> dict:
    """Elastic replica fleet (engine/replica.py + engine/router.py):

    * hydration time-to-ready vs history size, WAL-only (linear) vs
      snapshot-hydrated (~flat) — _bench_replica_ready_sweep;
    * a LIVE fleet: primary + read replicas as separate OS processes
      behind the in-process router — end-to-end p50/p95 through the
      router front door with 1 vs 2 replicas (the elasticity evidence),
      per-replica request spread, exported staleness lag (scraped from
      the router's real /metrics HTTP surface), and a SIGKILL of one
      replica under live load (zero lost queries = the failover
      evidence). tests/replica_canary.py gates all of it in CI.
    """
    import tempfile
    import urllib.request

    out = _bench_replica_ready_sweep()
    # 10s windows: the elasticity gate compares phase p95s, and with
    # ~20 qps of closed-loop traffic a 6s window leaves ~100 post-warmup
    # samples — p95 is then set by ~5 queue-alignment outliers and the
    # 1-vs-2-replica comparison flakes. 10s windows + 2s warmup keep the
    # estimate inside the phases' true separation (~2x).
    load_s = float(os.environ.get("BENCH_REPLICA_LOAD_S", 10.0))
    clients = int(os.environ.get("BENCH_REPLICA_CLIENTS", 8))
    tmp = tempfile.mkdtemp(prefix="bench_replica_")
    fleet = _ReplicaFleet(tmp)
    try:
        fleet.start_router()
        fleet.start_primary()
        fleet.start_replica("r1")
        one = fleet.run_load(load_s, clients=clients, warmup_s=2.0)
        fleet.start_replica("r2")
        r1_before = {e.replica_id: e.requests
                     for e in fleet.router.endpoints()}.get("r1", 0)
        two = fleet.run_load(load_s, clients=clients, warmup_s=2.0)
        eps = {e.replica_id: e for e in fleet.router.endpoints()}
        out.update({
            "replica_fleet_clients": clients,
            "replica_query_cost_ms": float(
                fleet.base_env["REPLICA_BENCH_QUERY_COST_MS"]),
            "replica_p50_ms_1": one.get("p50_ms"),
            "replica_p95_ms_1": one.get("p95_ms"),
            "replica_p50_ms_2": two.get("p50_ms"),
            "replica_p95_ms_2": two.get("p95_ms"),
            # phase-2 spread: requests each replica served while BOTH
            # were up (r1's phase-1 traffic subtracted out)
            "replica_requests_r1": eps["r1"].requests - r1_before,
            "replica_requests_r2": eps["r2"].requests,
            "replica_max_staleness_ticks": max(
                e.staleness_ticks for e in eps.values()),
        })
        if one.get("p95_ms") and two.get("p95_ms"):
            out["replica_p95_ratio_2v1"] = round(
                two["p95_ms"] / one["p95_ms"], 3)
        # the exported surface itself: per-replica staleness must be on
        # the router's real /metrics endpoint (acceptance criterion)
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{fleet.router.port}/metrics",
            timeout=10).read().decode()
        out["replica_staleness_exported"] = (
            'pathway_tpu_replica_staleness_ticks{replica="r1"}' in metrics
            and 'pathway_tpu_replica_staleness_ticks{replica="r2"}'
            in metrics)
        # failover: SIGKILL r1 mid-window; the router must fail its
        # in-flight queries over to r2 — zero lost end to end
        kill = fleet.run_load(load_s, clients=clients,
                              kill_at_s=load_s / 3, kill_rid="r1")
        fleet.wait_deregistered("r1")
        out.update({
            "replica_kill_queries": kill["queries"],
            "replica_lost_queries": kill["lost"],
            "replica_failovers": fleet.router.failovers_total,
            "replica_p95_ms_after_kill": kill.get("p95_ms"),
            "replica_fleet_after_kill": sorted(
                e.replica_id for e in fleet.router.endpoints()),
        })
    finally:
        fleet.stop()
    out.update(_bench_replica_failover())
    return out


def _bench_replica_failover() -> dict:
    """Write-path failover wall-clock (PR 18): a registered primary +
    one caught-up replica; SIGSTOP the primary (a zombie, not a corpse:
    its sockets stay open, so only the heartbeat-staleness detector can
    declare it) and measure death-declaration -> promoted-primary
    heartbeat on the router's clock. Then SIGCONT the zombie: its next
    commit must refuse with FencedPrimaryError (counted from its
    stderr — each one is a split-brain write that did NOT land)."""
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bench_failover_")
    fleet = _ReplicaFleet(tmp)
    out: dict = {}
    try:
        fleet.start_router(write_paths=("/w",),
                           election_timeout_ms=1500)
        fleet.start_primary(register=True)
        fleet.start_replica("r1")
        fleet.sigstop("primary")
        promoted = fleet.wait_promoted(1)
        out["replica_failover_promotion_s"] = (
            None if fleet.router.failover_seconds is None
            else round(fleet.router.failover_seconds, 3))
        out["replica_promoted_member"] = promoted
        # wake the zombie: fencing, not luck, keeps the timeline single
        fleet.sigcont("primary")
        deadline = time.monotonic() + 60
        fenced = 0
        while time.monotonic() < deadline:
            # the error MESSAGE appears once per refused write; the bare
            # class name also shows up in traceback frames (over-counts)
            fenced = fleet.stderr_text("primary").count(
                "fenced primary: this writer holds fencing epoch")
            if fenced and fleet.procs["primary"].poll() is not None:
                break
            time.sleep(0.25)
        out["replica_fenced_writes"] = fenced
    finally:
        fleet.stop()
    return out


def bench_knn() -> dict:
    """Query latency against the largest slab that fits one chip.

    ``knn_p50_ms`` is DEVICE execution time per single-query search
    (measured by index.latency_probe: many searches in one dispatch — the
    number the <20 ms target is about). ``knn_e2e_*`` are end-to-end
    through this environment's dispatch path, with the measured dispatch
    floor reported next to them.
    """

    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    import jax
    import jax.numpy as jnp

    n = KNN_N
    while True:
        try:
            index = BruteForceKnnIndex(KNN_DIM, reserved_space=n,
                                       metric=KnnMetric.COS,
                                       dtype="bfloat16")
            rng = np.random.default_rng(0)
            ingest_start = time.perf_counter()
            chunk = min(1 << 19, n)
            # ingest through the DEVICE path (the production embed+index
            # route: vectors are born on-chip): per-chunk on-device RNG +
            # add_batch_device scatter — no 7.7 GB host→device transfer,
            # which would dominate wall time through a dev tunnel
            gen = jax.jit(
                lambda key: jax.random.uniform(
                    key, (chunk, KNN_DIM), jnp.bfloat16, -1.0, 1.0))
            for ci, base in enumerate(range(0, n, chunk)):
                m = min(chunk, n - base)
                vecs = gen(jax.random.PRNGKey(ci))
                index.add_batch_device(
                    [Pointer(base + i) for i in range(m)], vecs[:m])
            queries = rng.random((64, KNN_DIM), dtype=np.float32) * 2.0 - 1.0

            def run(batch, k=10):
                qs = [(Pointer(10**9 + i), batch[i], k, None)
                      for i in range(len(batch))]
                return index.search(qs)

            # first search uploads the slab + compiles the (1, N) kernel
            res = run(queries[:1])
            assert res[0] and len(res[0]) == 10
            ingest_s = time.perf_counter() - ingest_start

            dev_single = index.latency_probe(batch_size=1, k=10, reps=64)
            dev_batch64 = index.latency_probe(batch_size=64, k=10, reps=16)
            floor = _dispatch_floor_ms()
            lat = []
            for i in range(20):
                t0 = time.perf_counter()
                run(queries[i % 64:i % 64 + 1])
                lat.append((time.perf_counter() - t0) * 1e3)
            # bf16 top-10 for the int8 overlap probe (same vectors: the
            # int8 slab re-ingests identical PRNGKey chunks)
            bf16_top = [tuple(k for k, _ in r) for r in run(queries[:8])]
            out = {
                "knn_n_vectors": n,
                "knn_dim": KNN_DIM,
                "knn_dtype": "bfloat16",
                "knn_p50_ms": round(dev_single, 2),
                "knn_batch64_ms": round(dev_batch64, 2),
                "knn_vs_target": round(KNN_TARGET_P50_MS / dev_single, 3),
                "knn_e2e_p50_ms": round(float(np.percentile(lat, 50)), 2),
                "knn_e2e_p99_ms": round(float(np.percentile(lat, 99)), 2),
                "knn_dispatch_floor_ms": round(floor, 2),
                "knn_ingest_s": round(ingest_s, 1),
            }
            del index
            try:
                out.update(_bench_knn_int8(n, gen, chunk, queries, bf16_top))
            except Exception as e:  # noqa: BLE001 - int8 leg is additive
                out["knn_int8_error"] = f"{type(e).__name__}: {str(e)[:200]}"
            return out
        except (RuntimeError, MemoryError) as e:
            # HBM too small for this slab — release EVERYTHING the failed
            # attempt pinned on device (slab, chunk buffer, jitted gen)
            # before retrying, then halve
            index = vecs = gen = None  # noqa: F841
            import gc

            gc.collect()
            if n <= 1 << 20:
                return {"knn_error": str(e)[:200]}
            n //= 2


if __name__ == "__main__":
    main()
