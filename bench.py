"""Headline benchmark: RAG embed+index throughput (docs/sec/chip).

Measures the north-star path from BASELINE.md: documents → tokenize →
flagship encoder forward (BGE-small shape, bfloat16, jit) → KNN index add
(HBM slab scatter). Baseline target: ≥50k docs/sec on v5e-8 ⇒ 6250
docs/sec/chip. Prints ONE JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_DOCS_PER_SEC_PER_CHIP = 50_000 / 8
# 2048 docs/dispatch: amortizes per-execute overhead (and the tunnel RPC in
# the axon dev setup) — measured ~6% over 1024 at equal accuracy
BATCH = 2048
SEQ = 128
WORDS_PER_DOC = 90


def make_docs(n: int, seed: int = 0) -> list[str]:
    rng = np.random.default_rng(seed)
    vocab = [f"word{i}" for i in range(4096)]
    idx = rng.integers(0, len(vocab), size=(n, WORDS_PER_DOC))
    return [" ".join(vocab[j] for j in row) for row in idx]


def main() -> None:
    import jax

    from pathway_tpu.models.encoder import EncoderConfig, encode, init_params
    from pathway_tpu.models.tokenizer import HashTokenizer
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    config = EncoderConfig.bge_small()
    params = init_params(jax.random.PRNGKey(0), config)
    tokenizer = HashTokenizer(vocab_size=config.vocab_size, max_len=SEQ)
    index = BruteForceKnnIndex(config.hidden, reserved_space=1 << 17,
                               metric=KnnMetric.COS)

    encode_fn = jax.jit(
        lambda p, ids, mask: encode(p, ids, mask, config=config))

    docs = make_docs(BATCH * 4)

    def run_batch(batch_docs, key_base):
        ids, mask = tokenizer.batch(batch_docs, pad_to=SEQ)
        emb = np.asarray(encode_fn(params, ids, mask))
        for i, vec in enumerate(emb):
            index.add(Pointer(key_base + i), vec)
        return emb

    # warmup (compile + device clock ramp) + correctness probe: a doc must
    # retrieve itself. Several post-compile batches: the first dispatches of
    # a fresh process run measurably slower.
    run_batch(docs[:BATCH], 0)
    for w in range(3):
        run_batch(docs[:BATCH], 0)
    ids, mask = tokenizer.batch(docs[:8], pad_to=SEQ)
    probe = np.asarray(encode_fn(params, ids, mask))
    res = index.search([(Pointer(10**9), probe[3], 1, None)])
    assert res and res[0] and res[0][0][0] == Pointer(3), \
        f"self-retrieval failed: {res}"

    # timed: pipeline host tokenization against device compute — submit the
    # encode for batch i, tokenize batch i+1 while the TPU works, then drain.
    # Metric = sustained docs/sec over the timed window (first timed batch
    # dropped: it straddles the warmup boundary). Sustained, not per-batch
    # median — the number must be comparable to BASELINE.md's sustained
    # target, stalls included.
    n_batches = 0
    key_base = BATCH
    start = time.perf_counter()
    batch_times = []
    last_t = start
    ids, mask = tokenizer.batch(docs[:BATCH], pad_to=SEQ)
    pending = None  # (device_array, key_base)
    while True:
        fut = encode_fn(params, ids, mask)  # async dispatch
        next_docs = docs[((n_batches + 1) % 4) * BATCH:][:BATCH]
        ids, mask = tokenizer.batch(next_docs, pad_to=SEQ)  # overlaps device
        if pending is not None:
            emb, base = pending
            index.add_batch([Pointer(base + i) for i in range(len(emb))],
                            np.asarray(emb))
            now = time.perf_counter()
            batch_times.append(now - last_t)
            last_t = now
        pending = (fut, key_base)
        n_batches += 1
        key_base += BATCH
        elapsed = time.perf_counter() - start
        if elapsed > 15.0 and len(batch_times) >= 8:
            break
    emb, base = pending
    index.add_batch([Pointer(base + i) for i in range(len(emb))],
                    np.asarray(emb))
    now = time.perf_counter()
    batch_times.append(now - last_t)
    sustained = batch_times[1:]  # drop the warmup-straddling first batch
    docs_per_sec = BATCH * len(sustained) / float(np.sum(sustained))

    print(json.dumps({
        "metric": "RAG docs/sec/chip (embed+index)",
        "value": round(docs_per_sec, 1),
        "unit": "docs/s",
        "vs_baseline": round(docs_per_sec / BASELINE_DOCS_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
